#!/usr/bin/env python
"""Benchmarks: TPU engine vs the native C++ CPU engines (the
reference-equivalent baselines; the reference publishes no numbers —
BASELINE.md).

Default mode prints exactly ONE JSON line and exits 0, whatever happens —
including being SIGTERM/SIGKILL'd mid-run by an outer driver: a signal
handler flushes the best result collected so far.  The line is the
north-star config — 256 reads x 10 kb at 1% error (HiFi-like), alphabet
4, min_count = reads/4 — or the largest scale that completed, with a
``breakdown`` object (device dispatch counts, run-extend steps, band
growth events), the five-scenario parity gate as its own field (run in
its own subprocess with its own budget, per BASELINE.md), and — budget
permitting — dual/priority evidence lines under ``extra``.
``vs_baseline`` > 1 is a speedup over the CPU baseline.

Budget protocol (the round-3 failure mode was a largest-first attempt
ladder whose worst case could not fit the driver's outer wall clock):

* ``BENCH_TOTAL_BUDGET`` (default 1500 s) bounds the whole orchestration;
  every subprocess timeout is clipped to the remaining budget.
* the ladder walks SMALLEST-first (16x1000 -> 64x2000 -> 256x10000), so a
  valid device-platform JSON line exists within minutes and each success
  replaces the previous, smaller one.
* ``SIGTERM``/``SIGALRM`` print the best-so-far line and exit 0; an alarm
  fires shortly before the budget expires as a self-deadline.

Other modes (one JSON line per config):
  --grid      the reference criterion grid
              (``/root/reference/benches/consensus_bench.rs:9-33``):
              seq_len {1000, 10000} x num_samples {8, 30} x error
              {0.0, 0.01, 0.02}, alphabet 4, min_count = ns/4.
  --dual      dual-engine north-star point (two haplotypes).
  --priority  priority-chain north-star point.
  --smoke     16x1000 quick validation (also via BENCH_SMOKE=1).

``--trace DIR`` wraps the timed run in a ``jax.profiler`` trace.
``--platform {auto,cpu,device}`` pins the JAX backend (default auto:
probe, prefer the device, fall back to cpu).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TOTAL_BUDGET_S = int(os.environ.get("BENCH_TOTAL_BUDGET", "1500"))
#: per-rung re-probe cap: cheap enough to afford one per ladder rung, so
#: a transient tunnel outage demotes at most one rung, not the whole run
PER_RUNG_PROBE_S = int(os.environ.get("BENCH_RUNG_PROBE_TIMEOUT", "90"))
GATE_TIMEOUT_S = int(os.environ.get("BENCH_GATE_TIMEOUT", "420"))
#: per-rung caps, smallest first; the last (full) rung takes whatever
#: budget remains beyond the gate reserve
RUNG_CAPS_S = (420, 480)
GATE_RESERVE_S = 120

#: margin for the error-model band seed (initial_band config knob):
#: E0 = BAND_MARGIN + 2 * error_rate * seq_len keeps band growth at zero
#: for the generated workloads (VERDICT r3 #2)
BAND_MARGIN = 16

_START = time.monotonic()


def _remaining() -> float:
    return max(0.0, TOTAL_BUDGET_S - (time.monotonic() - _START))


def _time_stats(times):
    """``(min, median)`` of a non-empty list of wall times."""
    ts = sorted(times)
    n = len(ts)
    mid = n // 2
    median = ts[mid] if n % 2 else (ts[mid - 1] + ts[mid]) / 2
    return ts[0], median


def _runtime_events() -> dict:
    from waffle_con_tpu.runtime import events

    return events.summarize_events()


def _force_cpu_backend() -> None:
    """Pin JAX to the host CPU backend.  The ambient env pins
    ``JAX_PLATFORMS`` to the TPU plugin and a sitecustomize re-registers
    it, so ``jax.config.update`` before first backend use is the reliable
    switch (same approach as tests/conftest.py)."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def _run_captured(cmd, timeout_s):
    """Run ``cmd`` capturing output, with a timeout that kills the whole
    process *group* — a plain ``subprocess.run(timeout=...)`` SIGKILLs
    only the direct child and then blocks draining the pipes, which hangs
    forever if a TPU-runtime helper grandchild inherited them.

    Returns ``(rc | None, stdout, stderr)``; ``rc is None`` on timeout."""
    global _LIVE_CHILD
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        start_new_session=True,
    )
    _LIVE_CHILD = proc
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover - last resort
            out, err = "", ""
        return None, out, err
    finally:
        _LIVE_CHILD = None


def _last_json_line(stdout: str):
    """The last stdout line that parses as a JSON object (tolerates
    trailing runtime/log chatter), or ``None``."""
    for line in reversed((stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _probe_device(timeout_s):
    """Initialize the default JAX backend in a THROWAWAY subprocess with a
    hard wall-clock limit; returns ``(info_dict | None, diagnostic)``.

    A subprocess is the only safe probe: backend init here can hang
    indefinitely inside C++ (remote-compile tunnel), which no in-process
    try/except can bound."""
    code = (
        "import json, jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "x = (jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready();"
        "print(json.dumps({'platform': d[0].platform, 'n_devices': len(d)}))"
    )
    try:
        rc, out, err = _run_captured([sys.executable, "-c", code], timeout_s)
    except Exception as exc:  # pragma: no cover - probe plumbing
        return None, f"device probe error: {exc!r}"
    if rc is None:
        return None, f"device probe timed out after {timeout_s:.0f}s"
    if rc == 0:
        info = _last_json_line(out)
        if info is not None and isinstance(info.get("platform"), str):
            return info, "ok"
    tail = (err or out or "").strip().splitlines()
    return None, "device probe failed: " + " | ".join(tail[-4:])[-600:]


def _make_engine(kind, cfg, reads_or_chains):
    from waffle_con_tpu import (
        ConsensusDWFA,
        DualConsensusDWFA,
        PriorityConsensusDWFA,
    )

    engine = {
        "single": ConsensusDWFA,
        "dual": DualConsensusDWFA,
        "priority": PriorityConsensusDWFA,
    }[kind](cfg)
    for r in reads_or_chains:
        if kind == "priority":
            engine.add_sequence_chain(r)
        else:
            engine.add_sequence(r)
    return engine


def _parity_gate():
    """Five-scenario parity gate (BASELINE.md): jax-backend engines must
    reproduce the golden fixtures exactly.  Returns ``{scenario: bool}``."""
    from waffle_con_tpu import CdwfaConfigBuilder, DualConsensusDWFA
    from waffle_con_tpu.models.priority_consensus import PriorityConsensusDWFA
    from waffle_con_tpu.utils.fixtures import (
        load_dual_fixture,
        load_priority_fixture,
    )

    cfg = CdwfaConfigBuilder().wildcard(ord("*")).backend("jax").build()
    checks = {}

    def run_priority(name, include):
        chains, expected = load_priority_fixture(name, include, cfg.consensus_cost)
        engine = PriorityConsensusDWFA(cfg)
        for chain in chains:
            engine.add_sequence_chain(chain)
        got = engine.consensus()
        ok = got.sequence_indices == expected.sequence_indices and [
            [c.sequence for c in chain] for chain in got.consensuses
        ] == [[c.sequence for c in chain] for chain in expected.consensuses]
        return bool(ok)

    # single + errored + multi split + priority chains run through the
    # priority stack (as the reference's own fixture tests do)
    checks["single"] = run_priority("multi_exact_001", True)
    checks["errored"] = run_priority("multi_err_001", False)
    checks["multi_split"] = run_priority("multi_samesplit_001", True)
    checks["priority_chains"] = run_priority("priority_001", True)

    sequences, expected = load_dual_fixture("dual_001", True, cfg.consensus_cost)
    engine = DualConsensusDWFA(cfg)
    for s in sequences:
        engine.add_sequence(s)
    checks["dual_split"] = engine.consensus() == [expected]
    return checks


def _band_seed(seq_len, error_rate) -> int:
    return BAND_MARGIN + int(2 * error_rate * seq_len)


# -- observability plumbing (obs subsystem) ---------------------------------
#
# With ``--trace-out FILE`` (or WAFFLE_TRACE/WAFFLE_METRICS in the env) the
# timed runs record per-(backend, op) dispatch latency histograms and nested
# search/dispatch/device-sync spans; the evidence JSON then carries a
# ``metrics`` registry snapshot plus one SearchReport per timed iteration,
# and FILE receives the Chrome trace of the SLOWEST iteration (the one worth
# staring at in Perfetto).  Without any of those, the obs layer stays
# uninstalled and the timed path is identical to an instrumentation-free run.


def _obs_setup(trace_out):
    """Enable metrics + tracing when ``--trace-out`` asks for them;
    returns the live tracer (or ``None`` when tracing is off)."""
    from waffle_con_tpu.obs import enable_metrics, get_tracer, tracing_enabled

    if trace_out:
        enable_metrics(True)
        get_tracer().enable(True)
    return get_tracer() if tracing_enabled() else None


def _obs_iter_begin(tracer):
    if tracer is not None:
        tracer.clear()  # each timed iteration gets its own span buffer


def _obs_iter_end(tracer, engine, dt, reports, slowest):
    """Collect the iteration's SearchReport; keep the slowest
    iteration's trace events.  Returns the updated ``slowest``."""
    rep = getattr(engine, "last_search_report", None)
    if rep is not None:
        reports.append(rep.to_dict())
    if tracer is not None and dt > slowest[0]:
        return (dt, tracer.chrome_events())
    return slowest


def _obs_finish(out, tracer, trace_out, reports, slowest):
    """Attach the obs evidence to the bench line and write the trace."""
    from waffle_con_tpu.obs import metrics_enabled, registry
    from waffle_con_tpu.obs import audit as obs_audit

    if reports:
        out["search_report"] = reports[-1]
        out["search_reports"] = reports
    if metrics_enabled():
        out["metrics"] = registry().snapshot()
    audit_status = obs_audit.status()
    if audit_status is not None:
        out["audit"] = audit_status
    if tracer is not None and trace_out:
        tracer.write_chrome_trace(trace_out, events=slowest[1])
        out["trace_out"] = trace_out


def _emit(out, perfdb_kind=None):
    """Stamp and print one evidence line; optionally persist it.

    Every line bench.py prints goes through here: it carries the
    evidence schema major (``obs.perfdb.EVIDENCE_SCHEMA``), a
    ``phases`` dispatch breakdown when ``--profile`` is on, and — for
    the perf-gated modes — one appended perfdb record so the run joins
    the rolling CI baseline.  The append is best-effort: a read-only
    checkout must never fail the bench."""
    from waffle_con_tpu.obs import perfdb
    from waffle_con_tpu.obs import phases as obs_phases

    if obs_phases.profiling_enabled():
        snap = obs_phases.snapshot()
        if snap:
            out["phases"] = snap
    perfdb.stamp_evidence(out)
    print(json.dumps(out), flush=True)
    if perfdb_kind is None:
        return
    try:
        rec = perfdb.make_record(
            perfdb_kind,
            out.get("metric", perfdb_kind),
            float(out.get("value") or 0.0),
            str(out.get("unit", "")),
            platform=out.get("device_platform", "unknown"),
            parity=out.get("parity"),
        )
        breakdown = out.get("breakdown")
        if isinstance(breakdown, dict) and "run_cols" in breakdown:
            rec["run_cols"] = breakdown["run_cols"]
        # tie-heavy records carry their headline companions so the
        # trend table tells the whole story from one line; crash-drill
        # records carry their migration accounting, storm records their
        # per-iteration walls, and cache records their hit accounting
        # the same way
        for k in ("wall_s", "wall_median_s", "iter_walls_s",
                  "steps_per_s", "gang_occupancy",
                  "gang_commit_rate", "migrated", "restarted_started",
                  "wasted_work_s", "migration_jobs", "hit_rate",
                  "cache_hits", "checkpoint_jobs", "host_round_trips",
                  "syms_per_dispatch", "commits_per_dispatch"):
            v = out.get(k)
            if v is None and isinstance(breakdown, dict):
                v = breakdown.get(k)
            if v is not None:
                rec[k] = v
        if "phases" in out:
            rec["phases"] = out["phases"]
        path = perfdb.append_record(rec)
        print(f"perfdb: appended {perfdb_kind} record to {path}",
              file=sys.stderr)
    except Exception as exc:  # noqa: BLE001 - history is best-effort
        print(f"perfdb append failed: {exc!r}", file=sys.stderr)


def _append_mixed_w_record(out):
    """Second perfdb line for ``--serve-mix``: the mixed-W traffic
    class lands as its own ``serve-mix-mixed-w`` record (occupancy,
    compile count, parity bit) so ``perf_report.py --check`` can gate
    it independently of the base heterogeneous mix."""
    from waffle_con_tpu.obs import perfdb

    mixed = out.get("mixed_w")
    if not isinstance(mixed, dict):
        return
    try:
        rec = perfdb.make_record(
            "serve-mix-mixed-w",
            "serve_mix_mixed_w_jobs_per_s",
            float(mixed.get("jobs_per_s_ragged") or 0.0),
            "jobs/s",
            platform=out.get("device_platform", "unknown"),
            parity=mixed.get("parity"),
            ragged_occupancy=mixed.get("ragged_occupancy"),
            compiles_ragged=mixed.get("compiles_ragged"),
            mixed_w_groups=mixed.get("mixed_w_groups"),
            recenters=mixed.get("recenters"),
        )
        path = perfdb.append_record(rec)
        print(f"perfdb: appended serve-mix-mixed-w record to {path}",
              file=sys.stderr)
    except Exception as exc:  # noqa: BLE001 - history is best-effort
        print(f"perfdb append failed: {exc!r}", file=sys.stderr)


def _append_microbench_mega_record(out):
    """Second perfdb line for ``--microbench``: the MEGASTEP hot-loop
    throughput lands as its own ``microbench-mega`` record (steps/s,
    commits-per-dispatch, round trips) so ``perf_report.py --check``
    can trend/gate it independently of the plain run_extend number."""
    from waffle_con_tpu.obs import perfdb

    mega = out.get("mega")
    if not isinstance(mega, dict):
        return
    try:
        rec = perfdb.make_record(
            "microbench-mega",
            mega["metric"],
            float(mega.get("steps_per_s") or 0.0),
            "steps/s",
            platform=out.get("device_platform", "unknown"),
            parity=mega.get("parity"),
            syms_per_dispatch=mega.get("syms_per_dispatch"),
            host_round_trips=mega.get("host_round_trips"),
        )
        path = perfdb.append_record(rec)
        print(f"perfdb: appended microbench-mega record to {path}",
              file=sys.stderr)
    except Exception as exc:  # noqa: BLE001 - history is best-effort
        print(f"perfdb append failed: {exc!r}", file=sys.stderr)


def _gang_fields(counters) -> dict:
    """Frontier-gang occupancy/commit summary for an evidence breakdown."""
    groups = counters.get("gang_groups", 0)
    gi = counters.get("run_gang_injected", 0)
    gm = counters.get("run_gang_mispredict", 0)
    return {
        "gang_groups": groups,
        "gang_members": counters.get("gang_members", 0),
        "gang_occupancy": round(
            counters.get("gang_members", 0) / groups, 2
        ) if groups else 0.0,
        "gang_commit_rate": round(gi / (gi + gm), 4) if (gi + gm) else None,
    }


def bench_single(num_reads, seq_len, error_rate, trace=None, iters=5,
                 trace_out=None):
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.native import native_consensus
    from waffle_con_tpu.utils.example_gen import generate_test

    min_count = max(2, num_reads // 4)
    gen_start = time.perf_counter()
    truth, reads = generate_test(4, seq_len, num_reads, error_rate, seed=0)
    gen_time = time.perf_counter() - gen_start

    band = _band_seed(seq_len, error_rate)
    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend(backend)
        .initial_band(band)
        .build()
    )

    cpu_start = time.perf_counter()
    cpu_results = native_consensus(reads, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    # TPU engine: warm-up once (compile), then timed run
    def tpu_run():
        engine = _make_engine("single", cfg("jax"), reads)
        out = engine.consensus()
        return engine, out

    compile_start = time.perf_counter()
    engine, tpu_results = tpu_run()
    compile_time = time.perf_counter() - compile_start

    from waffle_con_tpu.ops.scorer import host_overlap_total

    if trace:
        import jax

        jax.profiler.start_trace(trace)
    tracer = _obs_setup(trace_out)
    overlap0 = host_overlap_total()
    times = []
    reports = []
    slowest = (-1.0, None)
    for _ in range(max(1, iters)):
        _obs_iter_begin(tracer)
        tpu_start = time.perf_counter()
        engine, tpu_results = tpu_run()
        dt = time.perf_counter() - tpu_start
        times.append(dt)
        slowest = _obs_iter_end(tracer, engine, dt, reports, slowest)
    tpu_min, tpu_time = _time_stats(times)
    if trace:
        import jax

        jax.profiler.stop_trace()

    stats = getattr(engine, "last_search_stats", {})
    counters = stats.get("scorer_counters", {})
    spec_cols = (
        counters.get("run_spec_cols", 0)
        + counters.get("run_dual_spec_cols", 0)
    )
    spec_committed = (
        counters.get("run_steps", 0) + counters.get("run_dual_steps", 0)
    )
    spec_iters = (
        counters.get("run_iters", 0) + counters.get("run_dual_iters", 0)
    )
    dispatches = sum(
        counters.get(k, 0)
        for k in (
            "push_calls", "run_calls", "stats_calls", "clone_calls",
            "clone_push_calls", "activate_calls", "finalize_calls",
            "arena_calls", "run_dual_calls",
        )
    )
    out = {
        "metric": f"consensus_{num_reads}x{seq_len}_wall_s",
        "value": round(tpu_min, 4),
        "value_min": round(tpu_min, 4),
        "value_median": round(tpu_time, 4),
        "wall_median_s": round(tpu_time, 4),
        "iter_walls_s": [round(t, 4) for t in times],
        "n_iters": len(times),
        "unit": "s",
        "mode": "north-star",
        "vs_baseline": round(cpu_time / tpu_min, 3),
        "cpu_baseline_s": round(cpu_time, 4),
        "parity": bool(
            [(c.sequence, c.scores) for c in tpu_results] == cpu_results
        ),
        "recovered_truth": bool(
            tpu_results and tpu_results[0].sequence == truth
        ),
        "gen_s": round(gen_time, 2),
        "breakdown": {
            "warmup_incl_compile_s": round(compile_time, 2),
            "consensus_len": len(tpu_results[0].sequence) if tpu_results else 0,
            "device_dispatches": dispatches,
            "run_extend_calls": counters.get("run_calls", 0),
            "run_extend_steps": counters.get("run_steps", 0),
            "run_mega_calls": counters.get("run_mega_calls", 0),
            "commits_per_dispatch": round(
                counters.get("run_steps", 0)
                / max(counters.get("run_calls", 0), 1), 2
            ),
            "host_round_trips": counters.get("host_round_trips", 0),
            "run_pallas_calls": counters.get("run_pallas_calls", 0),
            "push_calls": counters.get("push_calls", 0),
            "arena_calls": counters.get("arena_calls", 0),
            "arena_steps": counters.get("arena_steps", 0),
            "grow_events": counters.get("grow_e_events", 0),
            "replayed_cols": counters.get("replayed_cols", 0),
            "initial_band": band,
            "cols_per_iter": round(spec_cols / max(spec_iters, 1), 2),
            "spec_commit_rate": round(
                spec_committed / spec_cols, 4
            ) if spec_cols else 1.0,
            "host_overlap_s": round(host_overlap_total() - overlap0, 4),
            "nodes_explored": stats.get("nodes_explored", 0),
            "steps_per_s": round(
                (counters.get("run_steps", 0) + counters.get("push_calls", 0))
                / max(tpu_time, 1e-9)
            ),
            **_gang_fields(counters),
            "runtime_events": _runtime_events(),
        },
    }
    _obs_finish(out, tracer, trace_out, reports, slowest)
    return out


def bench_microbench(num_reads, seq_len, error_rate, iters=3):
    """Raw device hot-loop throughput: time ``run_extend`` engagements
    of the north-star geometry directly on a ``JaxScorer``, without the
    engine's host-side search bookkeeping.  This is the steps/s
    regression gate CI asserts a floor on — it isolates the per-step
    cost of the lean device loop, so a device-loop regression cannot
    hide behind host-side wins (or vice versa).

    Measures BOTH the K=1 baseline and the configured speculative
    block size (``WAFFLE_RUN_COLS``); the configured-K number is the
    gated metric, and the breakdown records ``cols_per_iter`` /
    ``spec_commit_rate`` / ``host_overlap_s`` so the perf trajectory
    shows *why* steps/s moved.

    Parity cross-check rides along for free: at 1% error and
    ``min_count = reads/4`` the whole sequence is one unambiguous run,
    so the appended bytes must equal the generator's ground truth — at
    every measured K.

    The MEGASTEP run path is measured alongside (same geometry, same
    configured K, ``run_extend(..., mega=True)``): its steps/s lands in
    a second ``microbench-mega`` perfdb record, and both modes report
    ``host_round_trips`` (blocking device syncs per engagement) and
    ``syms_per_dispatch`` (committed symbols per run dispatch) — the
    two quantities the megastep exists to move.
    """
    import os

    import numpy as np

    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.utils import envspec
    from waffle_con_tpu.ops.jax_scorer import JaxScorer, _run_cols
    from waffle_con_tpu.ops.scorer import host_overlap_total
    from waffle_con_tpu.utils.example_gen import generate_test

    min_count = max(2, num_reads // 4)
    truth, reads = generate_test(4, seq_len, num_reads, error_rate, seed=0)
    band = _band_seed(seq_len, error_rate)
    cfg = (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend("jax")
        .initial_band(band)
        .build()
    )
    scorer = JaxScorer(reads, cfg)
    budget = 2**31 - 1

    def engage(mega):
        h = scorer.root(np.ones(num_reads, dtype=bool))
        steps, code, appended, stats, _recs = scorer.run_extend(
            h, b"", budget, budget, 0, min_count, False, seq_len,
            mega=mega,
        )
        # force the deferred-sync fetch inside the timed window so the
        # gated number includes the full result cost, not just control
        stats.eds
        scorer.free(h)
        return steps, code, appended

    def measure(k, mega=False):
        """Timed engagements at K=k (optionally on the megastep path):
        returns a dict of steps/s, parity, commit/dispatch accounting."""
        prev = envspec.get_raw("WAFFLE_RUN_COLS")
        os.environ["WAFFLE_RUN_COLS"] = str(k)
        try:
            compile_start = time.perf_counter()
            steps, code, appended = engage(mega)  # warm-up compiles this K
            compile_s = time.perf_counter() - compile_start
            parity = appended == truth
            it0 = scorer.counters["run_iters"]
            sc0 = scorer.counters["run_spec_cols"]
            st0 = scorer.counters["run_steps"]
            rc0 = scorer.counters["run_calls"]
            rt0 = scorer.counters["host_round_trips"]
            best = None
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                steps, code, appended = engage(mega)
                dt = time.perf_counter() - t0
                if best is None or dt < best:
                    best = dt
                parity = parity and appended == truth
            spec = scorer.counters["run_spec_cols"] - sc0
            committed = scorer.counters["run_steps"] - st0
            calls = scorer.counters["run_calls"] - rc0
            n = max(1, iters)
            return {
                "steps_per_s": steps / max(best, 1e-9),
                "parity": parity,
                "commit_rate": committed / spec if spec else 1.0,
                "cols_per_iter": spec / max(
                    scorer.counters["run_iters"] - it0, 1
                ),
                "steps": steps,
                "code": code,
                "best": best,
                "compile_s": compile_s,
                "syms_per_dispatch": committed / max(calls, 1),
                "host_round_trips": round(
                    (scorer.counters["host_round_trips"] - rt0) / n, 2
                ),
            }
        finally:
            if prev is None:
                os.environ.pop("WAFFLE_RUN_COLS", None)
            else:
                os.environ["WAFFLE_RUN_COLS"] = prev

    cols = _run_cols()
    overlap0 = host_overlap_total()
    base = measure(1)
    plain = measure(cols)
    mega = measure(cols, mega=True)
    parity = plain["parity"] and base["parity"] and mega["parity"]
    return {
        "metric": f"microbench_run_extend_{num_reads}x{seq_len}_steps_per_s",
        "value": round(plain["steps_per_s"], 1),
        "unit": "steps/s",
        "mode": "microbench",
        "n_iters": max(1, iters),
        "steps": int(plain["steps"]),
        "stop_code": int(plain["code"]),
        "best_engagement_s": round(plain["best"], 4),
        "parity": bool(parity),
        "mega": {
            "metric": (
                f"microbench_run_mega_{num_reads}x{seq_len}_steps_per_s"
            ),
            "steps_per_s": round(mega["steps_per_s"], 1),
            "syms_per_dispatch": round(mega["syms_per_dispatch"], 1),
            "host_round_trips": mega["host_round_trips"],
            "stop_code": int(mega["code"]),
            "parity": bool(mega["parity"]),
            "warmup_incl_compile_s": round(mega["compile_s"], 2),
        },
        "breakdown": {
            "warmup_incl_compile_s": round(
                plain["compile_s"] + base["compile_s"], 2
            ),
            "initial_band": band,
            "run_cols": cols,
            "steps_per_s_k1": round(base["steps_per_s"], 1),
            "steps_per_s_mega": round(mega["steps_per_s"], 1),
            "cols_per_iter": round(plain["cols_per_iter"], 2),
            "spec_commit_rate": round(plain["commit_rate"], 4),
            "syms_per_dispatch": round(plain["syms_per_dispatch"], 1),
            "syms_per_dispatch_mega": round(mega["syms_per_dispatch"], 1),
            "host_round_trips": plain["host_round_trips"],
            "host_round_trips_mega": mega["host_round_trips"],
            "host_overlap_s": round(host_overlap_total() - overlap0, 4),
            "run_pallas_calls": scorer.counters.get("run_pallas_calls", 0),
            "runtime_events": _runtime_events(),
        },
    }


def bench_dual(num_reads, seq_len, error_rate, iters=5, trace_out=None):
    """Dual north-star: two haplotypes differing by 3 SNPs, half the reads
    each; CPU baseline is the complete C++ dual engine."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.native import native_dual_consensus
    from waffle_con_tpu.utils.example_gen import generate_test
    import numpy as np

    rng = np.random.default_rng(1)
    truth, reads1 = generate_test(4, seq_len, num_reads // 2, error_rate, seed=1)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=3, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    h2 = bytes(h2)
    from waffle_con_tpu.utils.example_gen import corrupt

    reads2 = [
        corrupt(h2, error_rate, np.random.default_rng(100 + i))
        for i in range(num_reads // 2)
    ]
    reads = list(reads1) + reads2

    min_count = max(2, num_reads // 4)
    band = _band_seed(seq_len, error_rate)
    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend(backend)
        .initial_band(band)
        .build()
    )

    cpu_start = time.perf_counter()
    cpu_results = native_dual_consensus(reads, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    def tpu_run():
        engine = _make_engine("dual", cfg("jax"), reads)
        return engine, engine.consensus()

    engine, tpu_results = tpu_run()
    tracer = _obs_setup(trace_out)
    times = []
    reports = []
    slowest = (-1.0, None)
    for _ in range(max(1, iters)):
        _obs_iter_begin(tracer)
        tpu_start = time.perf_counter()
        engine, tpu_results = tpu_run()
        dt = time.perf_counter() - tpu_start
        times.append(dt)
        slowest = _obs_iter_end(tracer, engine, dt, reports, slowest)
    tpu_min, tpu_time = _time_stats(times)

    stats = getattr(engine, "last_search_stats", {})
    counters = stats.get("scorer_counters", {})
    total_symbols = max(
        1,
        sum(
            len(c.consensus1.sequence)
            + (len(c.consensus2.sequence) if c.consensus2 else 0)
            for c in tpu_results[:1]
        ),
    )
    out = {
        "metric": f"dual_{num_reads}x{seq_len}_wall_s",
        "value": round(tpu_min, 4),
        "value_min": round(tpu_min, 4),
        "value_median": round(tpu_time, 4),
        "wall_median_s": round(tpu_time, 4),
        "iter_walls_s": [round(t, 4) for t in times],
        "n_iters": len(times),
        "unit": "s",
        "mode": "dual",
        "vs_baseline": round(cpu_time / tpu_min, 3),
        "cpu_baseline_s": round(cpu_time, 4),
        "parity": bool(tpu_results == cpu_results),
        "is_dual": bool(tpu_results and tpu_results[0].is_dual()),
        "breakdown": {
            "run_dual_calls": counters.get("run_dual_calls", 0),
            "run_dual_steps": counters.get("run_dual_steps", 0),
            "run_mega_calls": counters.get("run_mega_calls", 0),
            "run_dual_mega_calls": counters.get("run_dual_mega_calls", 0),
            "host_round_trips": counters.get("host_round_trips", 0),
            "run_calls": counters.get("run_calls", 0),
            "run_steps": counters.get("run_steps", 0),
            "arena_calls": counters.get("arena_calls", 0),
            "arena_steps": counters.get("arena_steps", 0),
            "arena_discards": counters.get("arena_discards", 0),
            "arena_stops": {
                k[-1]: v
                for k, v in sorted(counters.items())
                if k.startswith("arena_stop_")
            },
            "push_calls": counters.get("push_calls", 0),
            "clone_push_calls": counters.get("clone_push_calls", 0),
            "grow_events": counters.get("grow_e_events", 0),
            "dual_engagement": round(
                (
                    counters.get("run_dual_steps", 0)
                    + counters.get("arena_dual_steps", 0)
                )
                / total_symbols,
                3,
            ),
            **_gang_fields(counters),
            "runtime_events": _runtime_events(),
        },
    }
    _obs_finish(out, tracer, trace_out, reports, slowest)
    return out


def bench_tie_heavy(num_reads, seq_len, error_rate=0.02, iters=1,
                    dual_seq_len=None):
    """Tie-heavy worst case: the 2% error grid point whose cost ties
    force the engine off the arena fast path and onto forced single-
    step pops — exactly the geometry frontier-parallel speculation
    exists for.  Runs the single-engine grid shape (the pre-PR 4x10000
    record took 4615 s) plus one dual tie-heavy config, and reports
    throughput (higher-better, gated by perf_report --check) with wall,
    gang occupancy and gang-commit rate riding along in the record.

    The gated ``value`` is nodes/s: the workload is deterministic, so
    nodes_explored is a constant and nodes/s is exactly inverse wall —
    but unlike wall it composes with the rolling higher-is-better
    baseline machinery perf_report already applies to every kind.
    """
    outs = []
    single = bench_single(num_reads, seq_len, error_rate, iters=iters)
    wall = float(single["value"])
    nodes = single["breakdown"].get("nodes_explored", 0)
    single["metric"] = (
        f"tie_heavy_4x{seq_len}x{num_reads}_{error_rate}"
    )
    single["mode"] = "tie-heavy"
    single["wall_s"] = round(wall, 4)
    single["value"] = round(nodes / max(wall, 1e-9), 1)
    single["unit"] = "nodes/s"
    single["steps_per_s"] = single["breakdown"].get("steps_per_s")
    single["gang_occupancy"] = single["breakdown"].get("gang_occupancy")
    single["gang_commit_rate"] = single["breakdown"].get("gang_commit_rate")
    outs.append(single)

    if dual_seq_len:
        d = bench_dual(num_reads, dual_seq_len, error_rate, iters=iters)
        dwall = float(d["value"])
        dsteps = (
            d["breakdown"].get("run_steps", 0)
            + d["breakdown"].get("run_dual_steps", 0)
            + d["breakdown"].get("arena_steps", 0)
            + d["breakdown"].get("push_calls", 0)
        )
        d["metric"] = (
            f"tie_heavy_dual_4x{dual_seq_len}x{num_reads}_{error_rate}"
        )
        d["mode"] = "tie-heavy"
        d["wall_s"] = round(dwall, 4)
        d["value"] = round(dsteps / max(dwall, 1e-9), 1)
        d["unit"] = "steps/s"
        d["gang_occupancy"] = d["breakdown"].get("gang_occupancy")
        d["gang_commit_rate"] = d["breakdown"].get("gang_commit_rate")
        outs.append(d)
    return outs


def bench_priority(num_reads, seq_len, error_rate, iters=5, trace_out=None):
    """Priority north-star: 2-level chains splitting into two groups."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.native import native_priority_consensus
    from waffle_con_tpu.utils.example_gen import generate_test, corrupt
    import numpy as np

    truth, level0 = generate_test(4, seq_len // 2, num_reads, error_rate, seed=3)
    t1a, _ = generate_test(4, seq_len, 1, 0.0, seed=4)
    t1b = bytearray(t1a)
    t1b[seq_len // 3] = (t1b[seq_len // 3] + 1) % 4
    t1b[2 * seq_len // 3] = (t1b[2 * seq_len // 3] + 2) % 4
    t1b = bytes(t1b)
    chains = []
    for i in range(num_reads):
        level1_truth = t1a if i < num_reads // 2 else t1b
        lvl1 = corrupt(level1_truth, error_rate, np.random.default_rng(200 + i))
        chains.append([level0[i], lvl1])

    min_count = max(2, num_reads // 4)
    band = _band_seed(seq_len, error_rate)
    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend(backend)
        .initial_band(band)
        .build()
    )

    cpu_start = time.perf_counter()
    cpu_result = native_priority_consensus(chains, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    def tpu_run():
        engine = _make_engine("priority", cfg("jax"), chains)
        return engine, engine.consensus()

    engine, tpu_result = tpu_run()
    tracer = _obs_setup(trace_out)
    times = []
    reports = []
    slowest = (-1.0, None)
    for _ in range(max(1, iters)):
        _obs_iter_begin(tracer)
        tpu_start = time.perf_counter()
        engine, tpu_result = tpu_run()
        dt = time.perf_counter() - tpu_start
        times.append(dt)
        slowest = _obs_iter_end(tracer, engine, dt, reports, slowest)
    tpu_min, tpu_time = _time_stats(times)

    out = {
        "metric": f"priority_{num_reads}x{seq_len}_wall_s",
        "value": round(tpu_min, 4),
        "value_min": round(tpu_min, 4),
        "value_median": round(tpu_time, 4),
        "wall_median_s": round(tpu_time, 4),
        "iter_walls_s": [round(t, 4) for t in times],
        "n_iters": len(times),
        "unit": "s",
        "mode": "priority",
        "vs_baseline": round(cpu_time / tpu_min, 3),
        "cpu_baseline_s": round(cpu_time, 4),
        "parity": bool(tpu_result == cpu_result),
        "groups": len(tpu_result.consensuses),
        "runtime_events": _runtime_events(),
    }
    _obs_finish(out, tracer, trace_out, reports, slowest)
    return out


def bench_serve(num_jobs, num_reads, seq_len, error_rate, trace_out=None,
                supervised=False):
    """Serving-throughput mode: N concurrent north-star-shaped single
    jobs through :class:`ConsensusService`, measuring jobs/s, mean batch
    occupancy of the cross-job dispatcher, and p50/p95 per-job latency.

    One job is run serially first (warms the XLA compile cache so the
    timed window measures serving, not compilation) and its result
    doubles as the parity reference for the served job with the same
    seed.

    ``supervised=True`` routes every served job's dispatches through the
    fault-tolerant supervisor (the warmup stays unsupervised), which is
    where ``WAFFLE_FAULTS`` injection applies — the CI flight-recorder
    smoke uses this to make a served job demote deterministically."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.serve import ConsensusService, JobRequest, ServeConfig
    from waffle_con_tpu.utils.example_gen import generate_test

    min_count = max(2, num_reads // 4)
    band = _band_seed(seq_len, error_rate)
    builder = (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend("jax")
        .initial_band(band)
    )
    warm_cfg = builder.build()
    if supervised:
        builder = (
            builder.supervised(True)
            .dispatch_retries(1)
            .retry_backoff_s(0.0)
            .breaker_threshold(2)
        )
    cfg = builder.build()
    workloads = [
        generate_test(4, seq_len, num_reads, error_rate, seed=i)[1]
        for i in range(num_jobs)
    ]

    # warmup runs unsupervised: it only exists to absorb XLA compiles,
    # and keeping it outside the supervisor means WAFFLE_FAULTS
    # injection (supervisor-scoped) fires inside the *served* jobs
    warm_start = time.perf_counter()
    serial_reference = _make_engine(
        "single", warm_cfg, workloads[0]
    ).consensus()
    warm_time = time.perf_counter() - warm_start

    tracer = _obs_setup(trace_out)
    _obs_iter_begin(tracer)
    svc = ConsensusService(
        ServeConfig(
            workers=min(num_jobs, 8),
            queue_limit=max(8, 2 * num_jobs),
            batch_window_s=0.005,
            max_batch=8,
        )
    )
    t0 = time.perf_counter()
    handles = svc.submit_all(
        [
            JobRequest(kind="single", reads=tuple(reads), config=cfg)
            for reads in workloads
        ]
    )
    results = [h.result() for h in handles]
    wall = time.perf_counter() - t0
    stats = svc.stats()
    svc.close()

    latencies = sorted(h.latency_s for h in handles)
    p50 = latencies[len(latencies) // 2]
    p95 = latencies[min(len(latencies) - 1, int(len(latencies) * 0.95))]
    reports = [
        h.search_report.to_dict() for h in handles
        if h.search_report is not None
    ]
    out = {
        "metric": f"serve_{num_jobs}jobs_{num_reads}x{seq_len}_jobs_per_s",
        "value": round(num_jobs / wall, 4),
        "unit": "jobs/s",
        "mode": "serve",
        "jobs": num_jobs,
        "jobs_per_s": round(num_jobs / wall, 4),
        "wall_s": round(wall, 4),
        "mean_batch_occupancy": round(
            stats["dispatch"]["mean_batch_occupancy"], 4
        ),
        "p50_job_latency_s": round(p50, 4),
        "p95_job_latency_s": round(p95, 4),
        "num_reads": num_reads,
        "seq_len": seq_len,
        "warmup_s": round(warm_time, 4),
        "parity": bool(results[0] == serial_reference),
        "serve_stats": stats,
        "runtime_events": _runtime_events(),
    }
    # recompile + ragged-gang evidence (satellite of the paged band-state
    # arena): compile_total counts distinct (kernel, geometry) jit keys
    # seen by this process, ragged_mean_occupancy is run dispatches per
    # arena kernel call (0.0 when nothing ganged / WAFFLE_RAGGED=0)
    from waffle_con_tpu.ops.jax_scorer import compile_count

    out["compile_total"] = compile_count()
    out["ragged_mean_occupancy"] = round(
        stats["dispatch"].get("ragged_mean_occupancy", 0.0), 4
    )
    # rolling SLO snapshot (p50/p95/p99 + EWMA over dispatch latency and
    # job wall time) and any flight-recorder incidents the run produced
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.obs import slo as obs_slo

    out["slo"] = obs_slo.snapshot()
    out["incidents"] = [
        {k: i.get(k) for k in
         ("seq", "reason", "trace_id", "unix_time", "path")}
        for i in obs_flight.incidents()
    ]
    if supervised:
        out["supervised"] = True
    slowest = (wall, tracer.chrome_events()) if tracer is not None else (wall, None)
    _obs_finish(out, tracer, trace_out, reports, slowest)
    return out


def bench_serve_mix(num_jobs, error_rate=0.01):
    """Heterogeneous serving benchmark: ``num_jobs`` single jobs with
    heavy-tailed read counts and lengths (seeded Pareto draws, so every
    job is a distinct shape bucket) run through :class:`ConsensusService`
    twice — once with ragged dispatch disabled (``WAFFLE_RAGGED=0``, the
    bucketed baseline, which on all-distinct shapes degrades to
    occupancy-1 run clusters and per-shape recompiles) and once with the
    paged band-state arena ganging run dispatches across jobs.

    Reports jobs/s for both phases, the arena's mean gang occupancy vs
    the baseline's mean run-cluster occupancy, per-phase recompile
    deltas (``compile_count()``), and a parity bit over EVERY job
    against serial references.  Each phase runs twice (warmup + timed)
    so neither pays its compiles inside the timed window.

    A third **mixed-W traffic class** then repeats the ragged-on/off
    comparison on jobs whose band seeds land on three distinct pow2 E
    geometries (E in {8, 16, 32} -> natural W in {18, 34, 66}): with
    width-agnostic pages (``WAFFLE_RAGGED_MIXED_W``, default on) they
    gang through one stride-masked kernel; the pre-stride arena would
    have fragmented every one of them into solo dispatches.  The
    result rides in the ``mixed_w`` evidence dict and lands as its own
    ``serve-mix-mixed-w`` perfdb record."""
    import numpy as np

    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.utils import envspec
    from waffle_con_tpu.ops import ragged as ops_ragged
    from waffle_con_tpu.ops.jax_scorer import compile_count
    from waffle_con_tpu.serve import ConsensusService, JobRequest, ServeConfig
    from waffle_con_tpu.utils.example_gen import generate_test

    rng = np.random.default_rng(20260805)
    shapes = []
    for _ in range(num_jobs):
        n_reads = int(min(20, 4 + rng.pareto(1.5) * 3))
        seq_len = int(min(480, 120 + rng.pareto(1.5) * 80))
        shapes.append((n_reads, seq_len))
    jobs = []
    for i, (n_reads, seq_len) in enumerate(shapes):
        reads = generate_test(4, seq_len, n_reads, error_rate,
                              seed=1000 + i)[1]
        cfg = (
            CdwfaConfigBuilder()
            .min_count(max(2, n_reads // 4))
            .backend("jax")
            .initial_band(_band_seed(seq_len, error_rate))
            .build()
        )
        jobs.append((reads, cfg))

    # mixed-W class: same heavy-tail read counts, band seeds cycling
    # through three distinct pow2 E geometries (seed -> _next_pow2 E)
    mixed_shapes = []
    mixed_jobs = []
    band_seeds = (8, 12, 24)  # -> E 8 / 16 / 32, natural W 18 / 34 / 66
    for i in range(num_jobs):
        n_reads = int(min(16, 4 + rng.pareto(1.5) * 3))
        seq_len = int(min(360, 120 + rng.pareto(1.5) * 60))
        mixed_shapes.append((n_reads, seq_len, band_seeds[i % 3]))
        reads = generate_test(4, seq_len, n_reads, error_rate,
                              seed=5000 + i)[1]
        cfg = (
            CdwfaConfigBuilder()
            .min_count(max(2, n_reads // 4))
            .backend("jax")
            .initial_band(band_seeds[i % 3])
            .build()
        )
        mixed_jobs.append((reads, cfg))

    serial = [
        _make_engine("single", cfg, reads).consensus()
        for reads, cfg in jobs
    ]
    mixed_serial = [
        _make_engine("single", cfg, reads).consensus()
        for reads, cfg in mixed_jobs
    ]

    def run_phase(ragged_on, phase_jobs):
        prev = envspec.get_raw("WAFFLE_RAGGED")
        os.environ["WAFFLE_RAGGED"] = "1" if ragged_on else "0"
        ops_ragged.reset_arena()
        try:
            c0 = compile_count()
            results, wall, stats = None, 0.0, {}
            for _attempt in range(2):  # warmup, then timed
                svc = ConsensusService(
                    ServeConfig(
                        workers=min(num_jobs, 8),
                        queue_limit=max(8, 2 * num_jobs),
                        batch_window_s=0.005,
                        max_batch=8,
                    )
                )
                t0 = time.perf_counter()
                handles = svc.submit_all([
                    JobRequest(kind="single", reads=tuple(r), config=c)
                    for r, c in phase_jobs
                ])
                results = [h.result() for h in handles]
                wall = time.perf_counter() - t0
                stats = svc.stats()
                svc.close()
            return results, wall, stats, compile_count() - c0
        finally:
            if prev is None:
                os.environ.pop("WAFFLE_RAGGED", None)
            else:
                os.environ["WAFFLE_RAGGED"] = prev

    b_res, b_wall, b_stats, b_comp = run_phase(False, jobs)
    r_res, r_wall, r_stats, r_comp = run_phase(True, jobs)
    mb_res, mb_wall, _mb_stats, _mb_comp = run_phase(False, mixed_jobs)
    mr_res, mr_wall, mr_stats, mr_comp = run_phase(True, mixed_jobs)

    base_parity = all(r == s for r, s in zip(b_res, serial)) and all(
        r == s for r, s in zip(r_res, serial)
    )
    mixed_parity = all(
        r == s for r, s in zip(mb_res, mixed_serial)
    ) and all(r == s for r, s in zip(mr_res, mixed_serial))
    parity = base_parity and mixed_parity  # the headline bit covers all
    ragged_occ = r_stats.get("ragged", {}).get("mean_occupancy", 0.0)
    bucketed_occ = b_stats["dispatch"].get(
        "run_cluster_mean_occupancy", 0.0
    )
    mixed_ragged = mr_stats.get("ragged", {})
    mixed_w = {
        "jobs": num_jobs,
        "shapes": mixed_shapes,
        "band_seeds": list(band_seeds),
        "parity": mixed_parity,
        "jobs_per_s_ragged": round(num_jobs / mr_wall, 4),
        "jobs_per_s_bucketed": round(num_jobs / mb_wall, 4),
        "speedup": round(mb_wall / mr_wall, 4),
        "ragged_occupancy": round(
            mixed_ragged.get("mean_occupancy", 0.0), 4
        ),
        "mean_gang_rows": round(
            mixed_ragged.get("mean_gang_rows", 0.0), 4
        ),
        "mixed_w_groups": mixed_ragged.get("mixed_w_groups", 0),
        "groups": mixed_ragged.get("groups", 0),
        "recenters": mixed_ragged.get("recenters", 0),
        "compiles_ragged": mr_comp,
        "ragged_stats": mixed_ragged,
    }
    return {
        "metric": f"serve_mix_{num_jobs}jobs_jobs_per_s",
        "value": round(num_jobs / r_wall, 4),
        "unit": "jobs/s",
        "mode": "serve-mix",
        "jobs": num_jobs,
        "shapes": shapes,
        "jobs_per_s_ragged": round(num_jobs / r_wall, 4),
        "jobs_per_s_bucketed": round(num_jobs / b_wall, 4),
        "speedup": round(b_wall / r_wall, 4),
        "ragged_occupancy": round(ragged_occ, 4),
        "bucketed_run_occupancy": round(bucketed_occ, 4),
        "occupancy_ratio": round(ragged_occ / max(bucketed_occ, 1e-9), 4),
        "compiles_bucketed": b_comp,
        "compiles_ragged": r_comp,
        "compile_total": compile_count(),
        "parity": parity,
        "ragged_stats": r_stats.get("ragged", {}),
        "mixed_w": mixed_w,
        "dispatch_ragged": {
            k: v for k, v in r_stats["dispatch"].items()
            if k.startswith("ragged") or k.startswith("run_cluster")
        },
        "runtime_events": _runtime_events(),
    }


def _storm_mix(num_jobs, error_rate, supervised):
    """The seeded storm workload shared by ``--storm`` (in-process
    replicas) and ``--storm --procs`` (worker processes): the SAME
    heavy-tailed job shapes, priority classes, configs, and
    Poisson-burst arrival schedule, so the two harnesses measure
    routing/transport differences, not workload luck.

    Returns ``(shapes, priorities, jobs, offsets, arrival_span,
    large_threshold)`` where each ``jobs`` entry is ``(reads,
    base_config, serve_config)`` — base is always unsupervised (serial
    references), serve carries the supervisor knobs when asked."""
    import numpy as np

    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.utils.example_gen import generate_test

    rng = np.random.default_rng(20260805)
    large_threshold = 16
    shapes, priorities = [], []
    for i in range(num_jobs):
        if i % 5 == 3:  # mesh-large: promoted by the placement policy
            n_reads, seq_len = 24, 120
        else:
            n_reads = int(min(12, 3 + rng.pareto(1.5) * 2))
            seq_len = int(min(360, 100 + rng.pareto(1.5) * 60))
        shapes.append((n_reads, seq_len))
        priorities.append(int(rng.choice([0, 1, 2], p=[0.5, 0.3, 0.2])))

    def build_cfg(n_reads, seq_len, sup):
        builder = (
            CdwfaConfigBuilder()
            .min_count(max(2, n_reads // 4))
            .backend("jax")
            .initial_band(_band_seed(seq_len, error_rate))
        )
        if sup:
            builder = (
                builder.supervised(True)
                .dispatch_retries(1)
                .retry_backoff_s(0.0)
                .breaker_threshold(2)
            )
        return builder.build()

    jobs = []
    for i, (n_reads, seq_len) in enumerate(shapes):
        reads = tuple(
            generate_test(4, seq_len, n_reads, error_rate, seed=2000 + i)[1]
        )
        jobs.append(
            (reads, build_cfg(n_reads, seq_len, False),
             build_cfg(n_reads, seq_len, supervised))
        )

    # Poisson bursts: exponential inter-burst gaps, geometric burst sizes
    offsets, t, i = [], 0.0, 0
    while i < num_jobs:
        burst = int(rng.geometric(0.45))
        for _ in range(min(burst, num_jobs - i)):
            offsets.append(t)
            i += 1
        t += float(rng.exponential(0.004))
    arrival_span = offsets[-1] if offsets else 0.0
    return shapes, priorities, jobs, offsets, arrival_span, large_threshold


def bench_storm(num_jobs, replicas=2, error_rate=0.01, supervised=False,
                iters=2):
    """Scale-out storm harness (``--storm N``): a heavy-tailed, bursty
    job mix fired at the replicated front door.

    The mix draws read counts and lengths from seeded Pareto tails (like
    ``--serve-mix``), salts in mesh-large jobs that the placement policy
    promotes onto the sharded scorer, and spreads priorities over three
    classes.  Arrivals follow a Poisson burst process: exponentially
    spaced bursts of geometrically distributed size, so admission sees
    genuine queueing, not a smooth drip.

    Two timed phases run the SAME mix on the SAME arrival schedule —
    one replica, then ``replicas`` replicas — each preceded by an
    untimed warmup pass that absorbs XLA compiles, and each timed
    ``iters`` times (default 2) with the faster wall kept and every
    per-iteration wall recorded in the evidence (noise-robust on
    shared CI hosts; fault-armed phases time once).  Reports jobs/s for
    both, the multi/single speedup, p50/p95/p99 job latency, a
    per-replica occupancy/routing table, and a parity bit over every
    completed job (both phases) against serial references.

    ``supervised=True`` routes served jobs through the fault-tolerant
    supervisor (serial references stay unsupervised), which is where
    ``WAFFLE_FAULTS`` injection applies — the CI shedding demo demotes
    one replica's backend mid-storm and the front door reroutes.  The
    plan is armed for the TIMED multi-replica pass only (a bounded
    firing count would otherwise be consumed by the warmups and the
    single-replica baseline)."""
    from waffle_con_tpu.utils import envspec
    from waffle_con_tpu.ops import ragged as ops_ragged
    from waffle_con_tpu.ops.jax_scorer import compile_count
    from waffle_con_tpu.serve import (
        JobRequest,
        PlacementPolicy,
        ReplicatedConfig,
        ReplicatedService,
        ServeConfig,
    )
    from waffle_con_tpu.runtime import faults as runtime_faults

    fault_spec = ""
    if supervised and envspec.get_raw("WAFFLE_FAULTS"):
        # defuse the env plan now; re-armed just before the timed
        # multi-replica pass (see docstring)
        fault_spec = os.environ.pop("WAFFLE_FAULTS")
        runtime_faults.install(None)

    (shapes, priorities, jobs, offsets, arrival_span,
     large_threshold) = _storm_mix(num_jobs, error_rate, supervised)

    # serial references double as the base-compile warmup; the mesh
    # variants compile during each phase's untimed warmup pass
    serial = [
        _make_engine("single", base_cfg, reads).consensus()
        for reads, base_cfg, _serve_cfg in jobs
    ]

    policy = PlacementPolicy(large_read_threshold=large_threshold,
                             mesh_shards=2)
    base = ServeConfig(
        workers=min(num_jobs, 4),
        queue_limit=max(8, 2 * num_jobs),
        batch_window_s=0.005,
        max_batch=8,
        placement=policy,
    )

    def run_phase(n_replicas, arm=None):
        """One untimed warmup pass (absorbs XLA compiles), then timed
        passes.  Paired second-scale walls on a shared host are
        noise-fragile, so an unfaulted phase times TWO passes and keeps
        the faster (min-wall is the noise-robust throughput estimator);
        a fault-armed phase times exactly ONE pass — its bounded firing
        counts must land in a single measured storm.  Every pass's
        results are parity-checked, not just the kept one."""
        ops_ragged.reset_arena()
        timed_passes = 1 if arm is not None else max(1, iters)
        best, walls, parity_ok = None, [], True
        for _attempt in range(1 + timed_passes):
            if _attempt == 1 and arm is not None:
                arm()
            svc = ReplicatedService(
                ReplicatedConfig(replicas=n_replicas, base=base)
            )
            reqs = [
                JobRequest(kind="single", reads=reads, config=serve_cfg,
                           priority=prio)
                for (reads, _base_cfg, serve_cfg), prio
                in zip(jobs, priorities)
            ]
            t0 = time.perf_counter()
            handles = []
            for off, req in zip(offsets, reqs):
                lag = off - (time.perf_counter() - t0)
                if lag > 0:
                    time.sleep(lag)
                handles.append(svc.submit(req))
            results = [h.result() for h in handles]
            wall = time.perf_counter() - t0
            lats = sorted(h.latency_s for h in handles)
            stats = svc.stats()
            rep_stats = svc.replica_stats()
            svc.close()
            parity_ok = parity_ok and all(
                r == ref for r, ref in zip(results, serial)
            )
            if _attempt == 0:
                continue
            walls.append(wall)
            if best is None or wall < best[0]:
                best = (wall, stats, rep_stats, lats)
        return best + (walls, parity_ok)

    s_wall, _s_stats, _s_reps, _s_lat, s_walls, s_parity = run_phase(1)
    arm = None
    if fault_spec:
        arm = lambda: runtime_faults.install(  # noqa: E731
            runtime_faults.plan_from_env(fault_spec)
        )
    m_wall, m_stats, m_reps, m_lat, m_walls, m_parity = run_phase(
        replicas, arm=arm
    )
    if fault_spec:
        os.environ["WAFFLE_FAULTS"] = fault_spec

    parity = s_parity and m_parity
    p50 = m_lat[len(m_lat) // 2]
    p95 = m_lat[min(len(m_lat) - 1, int(len(m_lat) * 0.95))]
    p99 = m_lat[min(len(m_lat) - 1, int(len(m_lat) * 0.99))]
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.obs import slo as obs_slo

    out = {
        "metric": f"storm_{num_jobs}jobs_{replicas}r_jobs_per_s",
        "value": round(num_jobs / m_wall, 4),
        "unit": "jobs/s",
        "mode": "storm",
        "jobs": num_jobs,
        "replicas": replicas,
        "shapes": shapes,
        "priorities": priorities,
        "large_jobs": sum(
            1 for n, _ in shapes if n >= large_threshold
        ),
        "mesh_placed": m_stats["jobs"].get("mesh_placed", 0),
        "jobs_per_s": round(num_jobs / m_wall, 4),
        "jobs_per_s_single": round(num_jobs / s_wall, 4),
        "speedup_vs_single": round(s_wall / m_wall, 4),
        "wall_s": round(m_wall, 4),
        "wall_median_s": round(_time_stats(m_walls)[1], 4),
        "iter_walls_s": [round(w, 4) for w in m_walls],
        "iter_walls_single_s": [round(w, 4) for w in s_walls],
        "arrival_span_s": round(arrival_span, 4),
        "p50_job_latency_s": round(p50, 4),
        "p95_job_latency_s": round(p95, 4),
        "p99_job_latency_s": round(p99, 4),
        "parity": parity,
        "aged_pops": m_stats.get("aged_pops", 0),
        "per_replica": [
            {k: rep.get(k) for k in
             ("replica", "state", "routed", "demotions", "sheds",
              "readmits", "mean_batch_occupancy",
              "ragged_mean_occupancy", "devices")}
            for rep in m_reps
        ],
        "shed": {
            "demotions": sum(r.get("demotions", 0) for r in m_reps),
            "sheds": sum(r.get("sheds", 0) for r in m_reps),
            "readmits": sum(r.get("readmits", 0) for r in m_reps),
        },
        "compile_total": compile_count(),
        "slo": obs_slo.snapshot(),
        "incidents": [
            {k: inc.get(k) for k in
             ("seq", "reason", "trace_id", "unix_time", "path")}
            for inc in obs_flight.incidents()
        ],
        "runtime_events": _runtime_events(),
    }
    if supervised:
        out["supervised"] = True
    if fault_spec:
        out["faults"] = fault_spec
    return out


def bench_storm_procs(num_jobs, procs=2, error_rate=0.01,
                      kill_worker=False, trace_out=None,
                      supervised=False, iters=2):
    """Out-of-process storm (``--storm N --procs P``): the exact
    workload and arrival schedule of :func:`bench_storm`, fired at the
    :class:`~waffle_con_tpu.serve.procs.door.ProcFrontDoor` with real
    worker processes instead of in-process replicas.

    Two phases on the same mix: one worker process (baseline), then
    ``procs`` workers.  A phase spawns its door ONCE and reuses it for
    the untimed warmup pass (absorbs each worker's XLA compiles — the
    fleet shares the persistent compile cache, so later workers mostly
    load what the first compiled) plus ``iters`` timed passes (default
    2), keeping the faster wall and recording every per-iteration wall
    in the evidence line.  Every pass's results are parity-checked
    byte-for-byte against in-process serial references.

    ``kill_worker=True`` is the crash drill: during the (single) timed
    multi-worker pass the busiest worker is SIGKILLed after a third of
    the jobs have been submitted.  The front door must detect the dead
    socket, **migrate** the victim's started jobs from their last
    checkpoints (a dense ``WAFFLE_CKPT_INTERVAL_S`` is pinned for the
    drill), requeue the rest, and still finish with parity true and
    exactly one ``worker_lost`` flight incident.  The evidence line
    carries the migration accounting — ``migrated`` vs
    ``restarted_started`` counts, ``wasted_work_s`` (work lost between
    the last snapshot and the crash), and per-migrated-job post-kill
    wall vs from-scratch wall — and lands as its own
    ``storm-procs-ckpt`` perfdb kind, so crash drills never join the
    ``storm-procs`` trend baseline.

    ``trace_out`` arms the fleet observability plane (tracing +
    metrics): the multi-worker phase is captured as ONE stitched Chrome
    trace — door spans and worker spans on the same per-job timeline,
    flow arrows across the socket hop — written to ``trace_out``, the
    evidence line gains the federated ``metrics`` snapshot (worker
    series merged under ``worker=`` labels) plus a ``fleet`` block.

    ``supervised=True`` routes the *served* jobs through the
    fault-tolerant supervisor inside each worker (serial references and
    evidence baselines stay unsupervised), which is where
    ``WAFFLE_FAULTS`` injection applies: the spec is popped from the
    environment up front (serial refs must run clean) and re-exported
    only for the multi-worker phase, whose freshly spawned workers
    inherit it — the CI fleet-observability smoke uses this to prove a
    worker-side flight trigger surfaces as a door-side incident file."""
    import signal

    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.obs import metrics as obs_metrics
    from waffle_con_tpu.obs import slo as obs_slo
    from waffle_con_tpu.runtime import faults as runtime_faults
    from waffle_con_tpu.serve import (
        JobRequest,
        PlacementPolicy,
        ProcConfig,
        ProcFrontDoor,
    )
    from waffle_con_tpu.utils import envspec

    fault_spec = ""
    if supervised and envspec.get_raw("WAFFLE_FAULTS"):
        # defuse the env plan now (door-side serial refs run clean);
        # re-exported just before the multi-worker phase so only its
        # spawned workers inherit the injection
        fault_spec = os.environ.pop("WAFFLE_FAULTS")
        runtime_faults.install(None)

    tracer = _obs_setup(trace_out)

    (shapes, priorities, jobs, offsets, arrival_span,
     large_threshold) = _storm_mix(num_jobs, error_rate, supervised)

    anchor_idx = None
    if kill_worker:
        # dense snapshots for the drill: the default 30 s cadence would
        # outlive the whole run, leaving nothing to migrate from
        os.environ.setdefault("WAFFLE_CKPT_INTERVAL_S", "0.05")
        # the drill anchor: one deliberately long search, submitted
        # first, that is still mid-flight (checkpoints streaming) when
        # the SIGKILL fires.  The storm's own Pareto mix is too
        # short-lived to guarantee a checkpointed victim job, let
        # alone a measurable resumed-vs-scratch wall gap.
        from waffle_con_tpu import CdwfaConfigBuilder
        from waffle_con_tpu.utils.example_gen import generate_test

        a_reads, a_len, a_err = 10, 400, 0.025
        anchor_reads = tuple(
            generate_test(4, a_len, a_reads, a_err, seed=77)[1]
        )
        anchor_cfg = (
            CdwfaConfigBuilder()
            .min_count(max(2, a_reads // 4))
            .backend("jax")
            .initial_band(_band_seed(a_len, a_err))
            .build()
        )
        shapes.insert(0, (a_reads, a_len))
        priorities.insert(0, 2)
        jobs.insert(0, (anchor_reads, anchor_cfg, anchor_cfg))
        offsets.insert(0, 0.0)
        anchor_idx = 0

    # in-process serial references (also warms the door-side jax
    # import); per-job walls feed the migration accounting below
    serial = []
    serial_walls = []
    for reads, base_cfg, _serve_cfg in jobs:
        t_ref = time.perf_counter()
        serial.append(_make_engine("single", base_cfg, reads).consensus())
        serial_walls.append(time.perf_counter() - t_ref)

    policy = PlacementPolicy(large_read_threshold=large_threshold,
                             mesh_shards=2)

    def run_phase(n_procs, kill=False):
        door = ProcFrontDoor(ProcConfig(
            workers=n_procs,
            worker_slots=min(num_jobs, 4),
            queue_limit=max(8, 2 * num_jobs),
            batch_window_s=0.005,
            max_batch=8,
            placement=policy,
            name="storm",
        ))
        timed_passes = 1 if kill else max(1, iters)
        best, walls, parity_ok, killed = None, [], True, None
        kill_mono, kill_handles, warm_lats = None, None, None
        try:
            for _attempt in range(1 + timed_passes):
                reqs = [
                    JobRequest(kind="single", reads=reads,
                               config=(scfg if supervised else cfg),
                               priority=prio)
                    for (reads, cfg, scfg), prio in zip(jobs, priorities)
                ]
                t0 = time.perf_counter()
                handles = []
                for idx, (off, req) in enumerate(zip(offsets, reqs)):
                    lag = off - (time.perf_counter() - t0)
                    if lag > 0:
                        time.sleep(lag)
                    handles.append(door.submit(req))
                if (kill and _attempt == 1 and killed is None
                        and n_procs > 1):
                    # wait until the anchor job is provably deep into
                    # its search — its streamed checkpoint reports
                    # ``farthest_consensus`` past 60% of the target
                    # length — then kill the worker that owns it, so
                    # the SIGKILL destroys real progress that
                    # migration then recovers.  Also require every
                    # other started job on that worker to have
                    # snapshotted, so the drill migrates everything
                    # instead of restarting stragglers.
                    by_id = {h.job_id: h for h in handles}
                    anchor = handles[anchor_idx]
                    gate_len = 0.6 * shapes[anchor_idx][1]
                    victim, poll_t0 = None, time.perf_counter()
                    while time.perf_counter() - poll_t0 < 120.0:
                        if anchor.done():
                            break
                        ck = anchor.checkpoint or {}
                        progress = ((ck.get("body") or {})
                                    .get("state") or {}
                                    ).get("farthest_consensus", 0)
                        if (anchor.started_at is not None
                                and progress >= gate_len):
                            owner = next(
                                (w for w in door.worker_stats()
                                 if anchor.job_id in w["jobs"]
                                 and w["state"] == "up" and w["pid"]),
                                None,
                            )
                            if owner is not None and all(
                                h is None or h.done()
                                or h.started_at is None
                                or h.checkpoint is not None
                                for h in (by_id.get(j)
                                          for j in owner["jobs"])
                            ):
                                victim = owner
                                break
                        time.sleep(0.01)
                    if victim is None:  # anchor finished or never
                        # snapshotted in time: fall back to the
                        # busiest worker
                        victim = max(
                            (w for w in door.worker_stats()
                             if w["state"] == "up" and w["pid"]),
                            key=lambda w: w["outstanding"],
                        )
                    os.kill(victim["pid"], signal.SIGKILL)
                    killed = victim["worker"]
                    kill_mono = time.monotonic()
                results = [h.result() for h in handles]
                wall = time.perf_counter() - t0
                lats = sorted(h.latency_s for h in handles)
                parity_ok = parity_ok and all(
                    r == ref for r, ref in zip(results, serial)
                )
                if _attempt == 0:
                    # the warmup pass runs the same mix through the
                    # same door uninterrupted: its per-job walls are
                    # the from-scratch served baseline the kill
                    # drill's post-kill walls are judged against
                    warm_lats = [h.latency_s for h in handles]
                    continue
                if kill:
                    kill_handles = list(handles)
                walls.append(wall)
                if best is None or wall < best[0]:
                    best = (wall, lats)
            stats = door.stats()
            workers = door.worker_stats()
        finally:
            door.close()
        return best + (stats, workers, walls, parity_ok, killed,
                       kill_mono, kill_handles, warm_lats)

    (s_wall, _s_lat, _s_stats, _s_workers, s_walls,
     s_parity) = run_phase(1)[:6]
    if fault_spec:
        # restore the env plan for the multi-worker phase only: its
        # workers spawn after this and resolve WAFFLE_FAULTS lazily
        # (the door process itself stays defused)
        os.environ["WAFFLE_FAULTS"] = fault_spec
    if tracer is not None:
        # the written trace covers exactly the multi-worker phase
        tracer.clear()
    (m_wall, m_lat, m_stats, m_workers, m_walls, m_parity, killed,
     kill_mono, kill_handles, warm_lats) = run_phase(procs,
                                                     kill=kill_worker)
    trace_spans = 0
    if tracer is not None:
        trace_spans = sum(
            1 for ev in tracer.chrome_events() if ev.get("ph") == "X"
        )
        if trace_out:
            tracer.write_chrome_trace(trace_out)

    parity = s_parity and m_parity
    p50 = m_lat[len(m_lat) // 2]
    p95 = m_lat[min(len(m_lat) - 1, int(len(m_lat) * 0.95))]
    p99 = m_lat[min(len(m_lat) - 1, int(len(m_lat) * 0.99))]
    lost_incidents = [
        inc for inc in obs_flight.incidents()
        if inc.get("reason") == "worker_lost"
    ]

    out = {
        "metric": f"storm_procs_{num_jobs}jobs_{procs}p_jobs_per_s",
        "value": round(num_jobs / m_wall, 4),
        "unit": "jobs/s",
        "mode": "storm-procs-ckpt" if kill_worker else "storm-procs",
        "jobs": num_jobs,
        "procs": procs,
        "shapes": shapes,
        "priorities": priorities,
        "mesh_placed": m_stats["jobs"].get("mesh_placed", 0),
        "jobs_per_s": round(num_jobs / m_wall, 4),
        "jobs_per_s_single": round(num_jobs / s_wall, 4),
        "speedup_vs_single": round(s_wall / m_wall, 4),
        "wall_s": round(m_wall, 4),
        "wall_median_s": round(_time_stats(m_walls)[1], 4),
        "iter_walls_s": [round(w, 4) for w in m_walls],
        "iter_walls_single_s": [round(w, 4) for w in s_walls],
        "arrival_span_s": round(arrival_span, 4),
        "p50_job_latency_s": round(p50, 4),
        "p95_job_latency_s": round(p95, 4),
        "p99_job_latency_s": round(p99, 4),
        "parity": parity,
        "aged_pops": m_stats.get("aged_pops", 0),
        "per_worker": m_workers,
        "workers_participating": sum(
            1 for w in m_workers if w["routed"] > 0
        ),
        "requeues": sum(w["requeues"] for w in m_workers),
        "migrated": sum(w["migrations"] for w in m_workers),
        "restarted_started": sum(w["restarts"] for w in m_workers),
        "checkpoints": m_stats.get("checkpoints", {}),
        "worker_lost_incidents": len(lost_incidents),
        "fleet": {
            "per_worker_dispatch_p95_s": {
                w["worker"]: w.get("dispatch_p95_s") for w in m_workers
            },
            "stats_frames": m_stats.get("fleet", {}).get(
                "stats_frames", 0
            ),
            "incidents_forwarded": m_stats.get("fleet", {}).get(
                "incidents_forwarded", 0
            ),
            "span_events": m_stats.get("fleet", {}).get(
                "span_events", 0
            ),
            "trace_spans": trace_spans,
        },
        "slo": obs_slo.snapshot(),
        "incidents": [
            {k: inc.get(k) for k in
             ("seq", "reason", "trace_id", "unix_time", "path")}
            for inc in obs_flight.incidents()
        ],
        "runtime_events": _runtime_events(),
    }
    if obs_metrics.metrics_enabled():
        out["metrics"] = obs_metrics.registry().snapshot()
    if trace_out and tracer is not None:
        out["trace_out"] = trace_out
    if supervised:
        out["supervised"] = True
    if fault_spec:
        out["faults"] = fault_spec
    if kill_worker:
        from waffle_con_tpu.runtime import events as runtime_events

        out["kill_worker"] = killed or True
        rescued = runtime_events.get_events("worker_jobs_rescued")
        out["wasted_work_s"] = round(
            sum(float(ev.get("wasted_s", 0.0)) for ev in rescued), 4
        )
        # per-migrated-job accounting: post-kill wall (kill -> finish
        # on the survivor, resumed from the checkpoint) vs the same
        # job's from-scratch wall through the same door (the warmup
        # pass) — the headline migration win.  The serial wall rides
        # along for reference; it is not comparable (the serving stack
        # adds per-dispatch batching overhead a serial run never pays).
        by_id = {h.job_id: (i, h)
                 for i, h in enumerate(kill_handles or [])}
        migration_jobs = []
        for ev in rescued:
            for jid in ev.get("migrated_jobs", ()):
                entry = by_id.get(jid)
                if entry is None or kill_mono is None:
                    continue
                idx, handle = entry
                if handle.finished_at is None:
                    continue
                migration_jobs.append({
                    "job": jid,
                    "post_kill_wall_s": round(
                        handle.finished_at - kill_mono, 4
                    ),
                    "scratch_wall_s": round(
                        (warm_lats or serial_walls)[idx], 4
                    ),
                    "serial_wall_s": round(serial_walls[idx], 4),
                })
        out["migration_jobs"] = migration_jobs
    return out


def bench_storm_cache(num_jobs, error_rate=0.03, iters=2):
    """Duplicate-heavy + superset-heavy cache storm (``--storm N
    --cache``): measures the content-addressed consensus cache at
    :class:`~waffle_con_tpu.serve.service.ConsensusService` admission.

    The mix derives from ``max(2, num_jobs // 4)`` unique single-kind
    jobs; each unique spawns three cache-traffic variants:

    * an **exact duplicate** with the reads permuted — must be served
      from the exact-hit tier (``CACHED``, ``started_at is None``:
      zero worker dispatches) with per-read scores remapped to the
      submitted order;
    * a **certify superset** (the unique's reads plus a copy of its
      consensus sequence) — the cached result becomes a proposal that
      one exact DWFA scoring pass proves optimal (``CERTIFIED``);
    * a **checkpoint superset** (the unique's reads plus one extra
      noisy read) — certification fails (the extra read raises the
      optimal cost), so the search resumes from the unique's deposited
      last bound-free checkpoint instead of starting from scratch
      (``DONE``, byte-identical by the no-incumbent-pruning argument
      in ``serve/cache``).

    Each of the ``iters`` timed iterations builds a FRESH service
    (fresh cache): a seed phase submits the uniques and waits for them
    (deposits land), then the timed phase fires every variant.  Every
    cache-served result is parity-checked byte-for-byte against a
    from-scratch serial reference computed on the variant's exact read
    order, exact-hit counts are checked deterministic (one per
    duplicate, all dispatch-free), and the evidence line carries the
    per-checkpoint-job resumed-vs-scratch walls (the overlap-reuse
    win) plus the aggregate ``hit_rate`` the perfdb ``storm-cache``
    trend gate rides on."""
    import numpy as np

    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.obs import metrics as obs_metrics
    from waffle_con_tpu.obs import slo as obs_slo
    from waffle_con_tpu.ops import ragged as ops_ragged
    from waffle_con_tpu.serve import (
        ConsensusService,
        JobRequest,
        JobStatus,
        ServeConfig,
    )
    from waffle_con_tpu.utils.example_gen import generate_test

    rng = np.random.default_rng(20260807)
    n_unique = max(2, num_jobs // 4)

    uniques = []  # (reads, cfg, seq_len)
    for i in range(n_unique):
        n_reads = int(rng.integers(6, 11))
        seq_len = int(rng.integers(140, 200))
        reads = tuple(
            generate_test(4, seq_len, n_reads, error_rate,
                          seed=3000 + i)[1]
        )
        cfg = (
            CdwfaConfigBuilder()
            .min_count(max(2, n_reads // 4))
            .backend("jax")
            .initial_band(_band_seed(seq_len, error_rate))
            .build()
        )
        uniques.append((reads, cfg, seq_len))

    def _serial(reads, cfg, passes=1):
        """From-scratch reference + wall; ``passes=2`` keeps the faster
        wall (the honest scratch baseline resumed walls are judged
        against — pass one may still absorb an XLA compile)."""
        ref, wall = None, None
        for _ in range(passes):
            t0 = time.perf_counter()
            ref = _make_engine("single", cfg, reads).consensus()
            w = time.perf_counter() - t0
            wall = w if wall is None else min(wall, w)
        return ref, wall

    seed_refs = [_serial(reads, cfg)[0] for reads, cfg, _ in uniques]

    # the three cache-traffic variants per unique, each with its own
    # serial reference on the EXACT submitted read order (per-read
    # scores follow read order, so a permuted duplicate has a permuted
    # reference); only the checkpoint-superset variant's scratch wall
    # is evidence, so only it pays a second timing pass
    variants = []  # (tag, unique_idx, reads, cfg, ref, scratch_wall)
    for i, (reads, cfg, seq_len) in enumerate(uniques):
        perm = [int(p) for p in rng.permutation(len(reads))]
        dup_reads = tuple(reads[j] for j in perm)
        extra = generate_test(4, seq_len, 1, 0.05, seed=9000 + i)[1][0]
        for tag, v_reads, passes in (
            ("dup", dup_reads, 1),
            ("cert", reads + (seed_refs[i][0].sequence,), 1),
            ("ckpt", reads + (extra,), 2),
        ):
            ref, scratch = _serial(v_reads, cfg, passes)
            variants.append((tag, i, v_reads, cfg, ref, scratch))

    saved_env = {
        k: os.environ.get(k)
        for k in ("WAFFLE_CACHE", "WAFFLE_CKPT_INTERVAL_S")
    }
    os.environ["WAFFLE_CACHE"] = "1"
    # dense snapshots during the seed phase so every unique deposits a
    # final checkpoint for the superset tier to resume from
    os.environ["WAFFLE_CKPT_INTERVAL_S"] = "0.001"

    best = None
    walls, parity_ok = [], True
    exact_ok, ckpt_hits_total = True, 0
    try:
        for _iter in range(max(1, iters)):
            ops_ragged.reset_arena()
            svc = ConsensusService(ServeConfig(
                workers=min(n_unique, 4),
                queue_limit=max(8, 4 * num_jobs),
                batch_window_s=0.005,
                max_batch=8,
                name="storm-cache",
            ))
            try:
                # seed phase (untimed): deposits land before the storm
                seed_handles = [
                    svc.submit(JobRequest(kind="single", reads=reads,
                                          config=cfg))
                    for reads, cfg, _ in uniques
                ]
                seed_results = [h.result() for h in seed_handles]
                parity_ok = parity_ok and all(
                    r == ref for r, ref in zip(seed_results, seed_refs)
                )
                # deposits land asynchronously after result(): wait for
                # them so the timed phase sees a fully seeded cache
                t_dep = time.perf_counter()
                while (svc.stats().get("cache", {}).get("deposits", 0)
                       < n_unique
                       and time.perf_counter() - t_dep < 10.0):
                    time.sleep(0.005)
                time.sleep(0.05)  # checkpoint deposit follows result's

                t0 = time.perf_counter()
                handles = [
                    svc.submit(JobRequest(kind="single", reads=v_reads,
                                          config=cfg))
                    for _tag, _i, v_reads, cfg, _ref, _w in variants
                ]
                results = [h.result() for h in handles]
                wall = time.perf_counter() - t0

                parity_ok = parity_ok and all(
                    r == ref
                    for r, (_t, _i, _r, _c, ref, _w)
                    in zip(results, variants)
                )
                # exact duplicates must never touch a worker
                exact_ok = exact_ok and all(
                    h.status is JobStatus.CACHED
                    and h.started_at is None
                    for h, (tag, *_rest) in zip(handles, variants)
                    if tag == "dup"
                )
                cstats = svc.stats()["cache"]
                ckpt_hits_total += cstats.get("checkpoint", 0)
                ckpt_jobs = [
                    {
                        "unique": i,
                        "resumed_wall_s": round(h.latency_s, 4),
                        "scratch_wall_s": round(scratch, 4),
                    }
                    for h, (tag, i, _r, _c, _ref, scratch)
                    in zip(handles, variants)
                    if tag == "ckpt" and h.status is JobStatus.DONE
                ]
                statuses = [h.status.value for h in handles]
                lats = sorted(h.latency_s for h in handles)
            finally:
                svc.close()
            walls.append(wall)
            if best is None or wall < best[0]:
                best = (wall, cstats, ckpt_jobs, statuses, lats)
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    wall, cstats, ckpt_jobs, statuses, lats = best
    n_variants = len(variants)
    hits = (cstats.get("exact", 0) + cstats.get("certified", 0)
            + cstats.get("checkpoint", 0))
    p50 = lats[len(lats) // 2]
    p95 = lats[min(len(lats) - 1, int(len(lats) * 0.95))]
    resumed_total = sum(j["resumed_wall_s"] for j in ckpt_jobs)
    scratch_total = sum(j["scratch_wall_s"] for j in ckpt_jobs)
    out = {
        "metric": f"storm_cache_{num_jobs}jobs_jobs_per_s",
        "value": round(n_variants / wall, 4),
        "unit": "jobs/s",
        "mode": "storm-cache",
        "jobs": n_variants,
        "uniques": n_unique,
        "jobs_per_s": round(n_variants / wall, 4),
        "wall_s": round(wall, 4),
        "wall_median_s": round(_time_stats(walls)[1], 4),
        "iter_walls_s": [round(w, 4) for w in walls],
        "p50_job_latency_s": round(p50, 4),
        "p95_job_latency_s": round(p95, 4),
        "parity": parity_ok,
        # the tentpole evidence: hit-rate over the cache-traffic storm,
        # dispatch-free exact hits, and resumed-vs-scratch walls for
        # the checkpoint-superset tier
        "hit_rate": round(hits / n_variants, 4),
        "cache_hits": hits,
        "cache": cstats,
        "exact_hits_dispatch_free": exact_ok,
        "exact_hits": cstats.get("exact", 0),
        "certified_hits": cstats.get("certified", 0),
        "checkpoint_hits": cstats.get("checkpoint", 0),
        "checkpoint_hits_all_iters": ckpt_hits_total,
        "checkpoint_jobs": ckpt_jobs,
        "resumed_wall_total_s": round(resumed_total, 4),
        "scratch_wall_total_s": round(scratch_total, 4),
        "statuses": statuses,
        "slo": obs_slo.snapshot(),
        "incidents": [
            {k: inc.get(k) for k in
             ("seq", "reason", "trace_id", "unix_time", "path")}
            for inc in obs_flight.incidents()
        ],
        "runtime_events": _runtime_events(),
    }
    if obs_metrics.metrics_enabled():
        out["metrics"] = obs_metrics.registry().snapshot()
    return out


def bench_explain(num_reads, seq_len, error_rate):
    """Bottleneck explainer (``--explain``): ONE profiled single-engine
    search with dense frontier sampling, rendered as a human-readable
    timeline + per-kernel phase table on stderr (the evidence JSON line
    still goes to stdout, carrying the raw samples).

    This is the worked "where did the time go" flow the README
    documents: the phase table says which kernel family and phase
    dominates; the frontier timeline says what the search was doing
    while it happened (queue growth, cost-gap collapse, speculative
    commit-rate drops, ragged injections)."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.utils import envspec
    from waffle_con_tpu.obs import flight as obs_flight
    from waffle_con_tpu.obs import phases as obs_phases
    from waffle_con_tpu.utils.example_gen import generate_test

    # much denser than the always-on default of 64: device-stepped
    # searches finish in few pops, and the whole point here is timeline
    # resolution
    os.environ.setdefault("WAFFLE_FRONTIER_SAMPLE", "4")
    obs_phases.enable_profiling(True)
    min_count = max(2, num_reads // 4)
    truth, reads = generate_test(4, seq_len, num_reads, error_rate,
                                 seed=0)
    cfg = (
        CdwfaConfigBuilder()
        .min_count(min_count)
        .backend("jax")
        .initial_band(_band_seed(seq_len, error_rate))
        .build()
    )
    warm_start = time.perf_counter()
    _make_engine("single", cfg, reads).consensus()  # absorb compiles
    warm_s = time.perf_counter() - warm_start
    obs_phases.reset()
    obs_flight.reset()

    engine = _make_engine("single", cfg, reads)
    t0 = time.perf_counter()
    results = engine.consensus()
    wall = time.perf_counter() - t0

    frontier = [
        {k: v for k, v in r.items() if k not in ("ts", "kind", "trace_id")}
        for r in obs_flight.get_recorder().records()
        if r["kind"] == "frontier"
    ]
    snap = obs_phases.snapshot()
    totals = obs_phases.totals()
    total_s = sum(totals.values()) or 1e-9

    err = sys.stderr
    print("== dispatch phase breakdown (per kernel/op/K/geometry) ==",
          file=err)
    print(f"{'label':36s} {'count':>6s} {'mean_ms':>8s} "
          f"{'prep':>7s} {'device':>7s} {'xfer':>7s} {'post':>7s}",
          file=err)
    for label, row in snap.items():
        print(
            f"{label:36s} {row['count']:6d} {row['mean_ms']:8.2f} "
            f"{row['host_prep']:7.3f} {row['device_compute']:7.3f} "
            f"{row['transfer']:7.3f} {row['host_post']:7.3f}",
            file=err,
        )
    print("== where the dispatch time went ==", file=err)
    for phase in ("host_prep", "device_compute", "transfer", "host_post"):
        print(f"  {phase:15s} {totals[phase]:8.3f}s "
              f"({100 * totals[phase] / total_s:5.1f}%)", file=err)
    print(f"== search-frontier timeline ({len(frontier)} samples, every "
          f"{envspec.get_raw('WAFFLE_FRONTIER_SAMPLE')} pops) ==", file=err)
    print(f"{'t_s':>8s} {'pops':>7s} {'queue':>6s} {'live':>5s} "
          f"{'cost':>6s} {'gap':>5s} {'len':>6s} {'far':>6s} "
          f"{'commit':>7s} {'gangW':>5s} {'gangCR':>7s}", file=err)
    for s in frontier:
        gap = s.get("gap")
        commit = s.get("spec_commit_rate")
        gw = s.get("gang_width")
        gcr = s.get("gang_commit_rate")
        print(
            f"{s['t_s']:8.3f} {s['pops']:7d} {s['queue']:6d} "
            f"{s['live']:5d} {s['top_cost']:6d} "
            f"{'-' if gap is None else gap:>5} {s['top_len']:6d} "
            f"{s['farthest']:6d} "
            f"{'-' if commit is None else f'{commit:.3f}':>7} "
            f"{'-' if gw is None else gw:>5} "
            f"{'-' if gcr is None else f'{gcr:.3f}':>7}",
            file=err,
        )

    rep = getattr(engine, "last_search_report", None)
    out = {
        "metric": f"explain_{num_reads}x{seq_len}_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "mode": "explain",
        "warmup_incl_compile_s": round(warm_s, 2),
        "n_results": len(results),
        "frontier_sample_every": int(
            envspec.get_raw("WAFFLE_FRONTIER_SAMPLE")
        ),
        "frontier": frontier,
        "phase_totals": {k: round(v, 6) for k, v in totals.items()},
    }
    if rep is not None:
        out["search_report"] = rep.to_dict()
    return out


def _child_cmd(mode_args, platform):
    return [
        sys.executable,
        os.path.abspath(__file__),
        *mode_args,
        "--platform",
        platform,
    ]


def _run_child(mode_args, platform, timeout_s, label):
    """Run one bench child in a subprocess (hang- and crash-proof);
    returns ``(result_dict | None, diagnostic)``."""
    if timeout_s < 30:
        return None, f"{label}: skipped (only {timeout_s:.0f}s budget left)"
    try:
        rc, out, err = _run_captured(_child_cmd(mode_args, platform), timeout_s)
    except Exception as exc:  # pragma: no cover - subprocess plumbing
        return None, f"{label}: launch error: {exc!r}"
    if rc is None:
        return None, f"{label}: timed out after {timeout_s:.0f}s"
    result = _last_json_line(out)
    if result is not None and ("metric" in result or "checks" in result):
        return result, "ok"
    tail = (err or out or "").strip().splitlines()
    return None, f"{label}: rc={rc}: " + " | ".join(tail[-4:])[-600:]


_BEST = {
    "metric": "consensus_256x10000_wall_s",
    "value": 0,
    "unit": "s",
    "vs_baseline": 0,
    "parity": False,
    # literal copy of perfdb.EVIDENCE_SCHEMA: _flush_best runs in signal
    # context, where importing the stamper is off-limits
    # (tests/test_evidence_schema.py pins the two in sync)
    "schema": 2,
    "error": "no bench attempt completed",
}
_FLUSHED = False
#: the currently running bench child, so a signal can take it down with us
#: (children run in their own sessions, so the parent dying does NOT kill
#: them — an orphan would hold the TPU runtime for its full timeout)
_LIVE_CHILD = None


def _flush_best(signum=None, frame=None):
    """Print the best-so-far JSON line exactly once and exit 0 (installed
    for SIGTERM/SIGALRM: the driver killing us must still get a line)."""
    global _FLUSHED
    if _FLUSHED:
        # re-entrant signal while the first flush is mid-write: returning
        # resumes the interrupted write; exiting here would truncate it
        return
    _FLUSHED = True
    child = _LIVE_CHILD
    if child is not None:
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    _BEST.setdefault("backend_diag", {})["flushed_by"] = (
        f"signal {signum}" if signum is not None else "normal exit"
    )
    sys.stdout.write(json.dumps(_BEST) + "\n")
    sys.stdout.flush()
    os._exit(0)


def _north_star_orchestrated(args) -> None:
    """Default mode: probe the backend, walk a smallest-first ladder of
    subprocess attempts under a total budget, then gate + extras.  Always
    prints one JSON line and exits 0 — even on SIGTERM/SIGALRM."""
    signal.signal(signal.SIGTERM, _flush_best)
    signal.signal(signal.SIGINT, _flush_best)
    signal.signal(signal.SIGALRM, _flush_best)
    # self-deadline slightly inside the budget so we flush before the
    # driver's own timeout machinery can SIGKILL us
    signal.alarm(max(30, int(TOTAL_BUDGET_S - 15)))

    diag = {}
    probe_log = diag.setdefault("probes", [])
    #: None = unknown (must probe before trusting the device), True/False =
    #: the last probe/attempt outcome.  A single early outage must never
    #: demote the whole run (the round-4 official record was CPU-fallback
    #: because of exactly that), so the state resets to unknown after any
    #: device-side failure and every rung re-probes as needed.
    device_state = {"ok": None}

    def probe_now() -> bool:
        budget = min(PER_RUNG_PROBE_S, _remaining() - GATE_RESERVE_S)
        if budget < 20:
            probe_log.append("probe skipped (budget)")
            return False
        info, probe_msg = _probe_device(budget)
        probe_log.append(probe_msg)
        ok = info is not None and info.get("platform") != "cpu"
        if info is not None:
            diag["device"] = info
        device_state["ok"] = ok
        return ok

    def want_device() -> bool:
        if args.platform == "cpu":
            return False
        # a pinned-CPU environment can never yield a device backend; the
        # probe subprocess would only burn its full timeout 3x (one per
        # rung) before failing — observed as 3x90s in BENCH_r05.json
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            if device_state["ok"] is None:
                probe_log.append("probe skipped (JAX_PLATFORMS=cpu pinned)")
            device_state["ok"] = False
            return False
        if args.platform == "device":
            return True
        # cache the last probe outcome across rungs: a False answer is
        # as sticky as a True one (re-probing every rung re-paid the
        # probe timeout each time); only a device-side *attempt* failure
        # resets the state to None to force an outage re-probe
        if device_state["ok"] is not None:
            return device_state["ok"]
        return probe_now()

    _BEST["backend_diag"] = diag

    smoke = args.smoke or os.environ.get("BENCH_SMOKE") == "1"
    rungs = [(16, 1000)] if smoke else [(16, 1000), (64, 2000), (256, 10_000)]

    failures = []
    got_device = False
    #: replacement rank of _BEST: a device-platform line beats any CPU
    #: line regardless of scale; within a platform, larger rungs win
    best_rank = (-1, -1)

    def attempt(i, num_reads, seq_len, platform):
        cap = RUNG_CAPS_S[i] if i < len(RUNG_CAPS_S) else _remaining()
        timeout_s = min(cap, max(0, _remaining() - GATE_RESERVE_S))
        mode = ["--_run", "--reads", str(num_reads), "--len", str(seq_len),
                "--iters", str(args.iters)]
        if args.profile:
            mode += ["--profile"]
        if args.trace:
            mode += ["--trace", args.trace]
        if args.trace_out:
            mode += ["--trace-out", args.trace_out]
        label = f"attempt {num_reads}x{seq_len}@{platform}"
        result, msg = _run_child(mode, platform, timeout_s, label)
        if result is None:
            failures.append(msg)
            print(f"bench attempt failed: {msg}", file=sys.stderr)
        return result

    for i, (num_reads, seq_len) in enumerate(rungs):
        on_device = want_device()
        if not on_device and got_device:
            # a device line already exists and the device is unreachable:
            # a CPU result can never replace it (rank below), so don't
            # burn the budget producing one — try the next rung's probe
            continue
        result = attempt(
            i, num_reads, seq_len, "device" if on_device else "cpu"
        )
        if result is None and on_device:
            # a device failure may be the tunnel, not the workload: drop
            # to unknown (the next rung re-probes) and retry this rung on
            # the CPU so the ladder still climbs during an outage.  Once
            # a device line exists, a CPU result can never replace it
            # (rank below), so skip the retry and spend the budget on the
            # next rung's re-probe instead.
            device_state["ok"] = None
            if args.platform != "device" and not got_device:
                result = attempt(i, num_reads, seq_len, "cpu")
                on_device = False
            elif got_device:
                continue
        if result is None:
            break  # this scale failed on every usable platform
        got_device = got_device or on_device
        rank = (1 if on_device else 0, i)
        if rank > best_rank:
            best_rank = rank
            result["backend_diag"] = diag
            _BEST.clear()
            _BEST.update(result)
    if failures:
        diag["fallback_chain"] = failures
        _BEST["backend_diag"] = diag

    # parity gate: its own subprocess, its own budget, reported as its own
    # field — never inside a timed attempt (VERDICT r3 weak #2).  After a
    # trailing device failure the state is unknown: re-probe rather than
    # pointing the gate + extras (up to ~960s of subprocess timeouts) at
    # a dead tunnel
    if (
        got_device
        and device_state["ok"] is not True
        and args.platform == "auto"
    ):
        probe_now()
    gate_platform = (
        "device"
        if (
            got_device
            and (device_state["ok"] is True or args.platform == "device")
        )
        else "cpu"
    )
    gate_timeout = min(GATE_TIMEOUT_S, _remaining() - 10)
    gate_result, gate_msg = _run_child(
        ["--_gate"], gate_platform, gate_timeout, "parity gate"
    )
    if gate_result is not None and "checks" in gate_result:
        checks = gate_result["checks"]
        _BEST["parity_gate"] = checks
        _BEST["parity_gate_platform"] = gate_result.get("platform", gate_platform)
        _BEST["parity_gate_s"] = gate_result.get("wall_s")
        if "parity" in _BEST:
            _BEST["parity"] = bool(_BEST["parity"] and all(checks.values()))
    else:
        _BEST["parity_gate"] = {"skipped": gate_msg}

    # budget permitting, record dual + priority evidence (VERDICT r3 #2);
    # the jax-on-CPU fallback runs BOTH extras at reduced scales (the
    # arena kernel's per-iteration compute is sized for a TPU VPU, not a
    # serial CPU core)
    extras = {}
    dual_scale = (
        ["--dual"]
        if gate_platform == "device"
        else ["--dual", "--reads", "16", "--len", "1500"]
    ) + ["--iters", str(args.iters)]
    priority_scale = (
        ["--priority"]
        if gate_platform == "device"
        else ["--priority", "--reads", "16", "--len", "1000"]
    ) + ["--iters", str(args.iters)]
    for mode, label, budget_need in (
        (dual_scale, "dual", 300),
        (priority_scale, "priority", 240),
    ):
        if _remaining() - 20 < budget_need:
            extras[label] = "skipped (budget)"
            continue
        res, msg = _run_child(
            mode, gate_platform, min(budget_need, _remaining() - 20), label
        )
        extras[label] = res if res is not None else msg
    _BEST["extra"] = extras

    signal.alarm(0)
    _flush_best()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--grid", action="store_true")
    parser.add_argument("--dual", action="store_true")
    parser.add_argument("--priority", action="store_true")
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument(
        "--iters", type=int, default=5,
        help="timed iterations per bench point (min/median reported)",
    )
    parser.add_argument("--trace", default=None)
    parser.add_argument(
        "--trace-out", dest="trace_out", default=None,
        help="write a Chrome trace-event JSON (Perfetto-loadable) of the "
        "slowest timed iteration, and embed a metrics snapshot + per-"
        "iteration SearchReport in the evidence JSON",
    )
    parser.add_argument(
        "--microbench", action="store_true",
        help="raw run_extend hot-loop steps/s (no engine host logic); "
        "one JSON line with the parity cross-check",
    )
    parser.add_argument(
        "--assert-steps-floor", type=float, default=None, metavar="S",
        dest="steps_floor",
        help="with --microbench: exit 1 unless steps/s >= S and the "
        "parity cross-check passed (the CI regression gate)",
    )
    parser.add_argument(
        "--assert-mega-floor", type=float, default=None, metavar="S",
        dest="mega_floor",
        help="with --microbench: exit 1 unless the MEGASTEP path's "
        "steps/s >= S, its parity held, and its host_round_trips per "
        "engagement is strictly below the plain path's (the megastep "
        "CI regression gate)",
    )
    parser.add_argument(
        "--tie-heavy", action="store_true", dest="tie_heavy",
        help="tie-heavy worst case: the 2%% error single-engine grid "
        "shape (4x10000x8 full, smaller under --smoke) plus one dual "
        "tie-heavy config; emits tie_heavy perfdb records (nodes/s "
        "resp. steps/s, higher-better) carrying wall, gang occupancy "
        "and gang-commit rate",
    )
    parser.add_argument(
        "--assert-wall-ceiling", type=float, default=None, metavar="S",
        dest="wall_ceiling",
        help="with --tie-heavy: exit 1 unless every config's timed "
        "wall <= S seconds and parity held (the CI smoke gate)",
    )
    parser.add_argument(
        "--serve", type=int, default=None, metavar="N",
        help="serving-throughput mode: N concurrent jobs through "
        "ConsensusService; reports jobs/s, mean batch occupancy, and "
        "p50/p95 job latency",
    )
    parser.add_argument(
        "--serve-mix", type=int, default=None, metavar="N",
        dest="serve_mix",
        help="heterogeneous serving mode: N jobs with heavy-tailed "
        "read counts/lengths (every job a distinct shape), run both "
        "bucketed (WAFFLE_RAGGED=0) and ragged; reports jobs/s, gang "
        "occupancy vs the bucketed baseline, recompile deltas, and an "
        "all-jobs parity bit",
    )
    parser.add_argument(
        "--storm", type=int, default=None, metavar="N",
        help="scale-out storm harness: N jobs with heavy-tailed sizes, "
        "three priority classes, mesh-large jobs and Poisson-burst "
        "arrivals, fired at the replicated front door; reports jobs/s "
        "vs a single-replica baseline on the same schedule, "
        "p50/p95/p99 job latency, a per-replica table, and an "
        "all-jobs parity bit",
    )
    parser.add_argument(
        "--replicas", type=int, default=2, metavar="R",
        help="with --storm: replica count for the multi-replica phase",
    )
    parser.add_argument(
        "--procs", type=int, default=None, metavar="P",
        help="with --storm: drive the storm through the out-of-process "
        "front door with P real worker processes (instead of in-process "
        "replicas); reports jobs/s vs a single-worker-process baseline "
        "on the same schedule, a per-worker table, and the parity bit",
    )
    parser.add_argument(
        "--kill-worker", action="store_true", dest="kill_worker",
        help="with --storm --procs: crash drill — SIGKILL the busiest "
        "worker mid-storm; the run must still finish with parity true "
        "(jobs requeued/restarted on the survivors) and records the "
        "worker_lost incident; never appends a perfdb record",
    )
    parser.add_argument(
        "--cache", action="store_true", dest="storm_cache",
        help="with --storm: duplicate-heavy + superset-heavy cache "
        "storm through the content-addressed consensus cache; reports "
        "hit rate per tier (exact/certified/checkpoint), dispatch-free "
        "exact hits, resumed-vs-scratch walls for checkpoint-superset "
        "jobs, and a parity bit over every cache-served result",
    )
    parser.add_argument(
        "--serve-supervised", action="store_true",
        help="with --serve: run the served jobs under the fault-"
        "tolerant supervisor (warmup stays unsupervised), so "
        "WAFFLE_FAULTS injection applies to the serving path — used by "
        "the CI flight-recorder smoke",
    )
    parser.add_argument(
        "--profile", action="store_true",
        help="enable phase-attributed dispatch profiling (WAFFLE_PROFILE): "
        "evidence lines grow a 'phases' histogram snapshot (host_prep / "
        "device_compute / transfer / host_post per kernel family).  Adds "
        "a device fence per dispatch, so timed numbers shift — never "
        "combine with --assert-steps-floor comparisons against "
        "unprofiled baselines",
    )
    parser.add_argument(
        "--explain", action="store_true",
        help="bottleneck explainer: one profiled single-engine search "
        "with dense frontier sampling; prints a phase table + search-"
        "frontier timeline to stderr and an mode=explain evidence line "
        "to stdout",
    )
    parser.add_argument(
        "--platform", choices=("auto", "cpu", "device"), default="auto"
    )
    # hidden: one in-process bench attempt / gate run (orchestrator children)
    parser.add_argument("--_run", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--_gate", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--reads", type=int, default=None, help=argparse.SUPPRESS)
    parser.add_argument("--len", type=int, dest="seq_len", default=None,
                        help=argparse.SUPPRESS)
    args = parser.parse_args()

    # in-process modes pin the backend themselves; the orchestrated default
    # never touches jax in the parent (children carry --platform)
    if args.profile:
        # env (not an import) so the orchestrated parent stays jax-free
        # and subprocess children inherit it
        os.environ["WAFFLE_PROFILE"] = "1"

    if args.storm:
        # replicas pin to disjoint CPU device slices: make sure the host
        # platform exposes several virtual devices BEFORE jax loads
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    if args.platform == "cpu" and (
        args._run or args._gate or args.grid or args.dual or args.priority
        or args.serve or args.serve_mix or args.storm or args.microbench
        or args.explain or args.tie_heavy
    ):
        _force_cpu_backend()

    if args.explain:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        smoke = args.smoke or os.environ.get("BENCH_SMOKE") == "1"
        out = bench_explain(
            args.reads or (16 if smoke else 64),
            args.seq_len or (1000 if smoke else 2000),
            0.01,
        )
        out["device_platform"] = _current_platform()
        _emit(out, perfdb_kind="explain")
        return

    if args.microbench:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        smoke = args.smoke or os.environ.get("BENCH_SMOKE") == "1"
        out = bench_microbench(
            args.reads or (16 if smoke else 256),
            args.seq_len or (1000 if smoke else 10_000),
            0.01,
            iters=args.iters,
        )
        out["device_platform"] = _current_platform()
        _emit(out, perfdb_kind="microbench")
        _append_microbench_mega_record(out)
        if args.steps_floor is not None:
            ok = out["parity"] and out["value"] >= args.steps_floor
            if not ok:
                print(
                    f"FAIL: steps/s {out['value']} < floor "
                    f"{args.steps_floor} or parity lost "
                    f"(parity={out['parity']})",
                    file=sys.stderr,
                )
                sys.exit(1)
        if args.mega_floor is not None:
            mega = out.get("mega", {})
            ok = mega.get("parity", False) and (
                mega.get("steps_per_s", 0) >= args.mega_floor
            )
            if not ok:
                print(
                    f"FAIL: mega steps/s {mega.get('steps_per_s')} < "
                    f"floor {args.mega_floor} or mega parity lost "
                    f"(parity={mega.get('parity')})",
                    file=sys.stderr,
                )
                sys.exit(1)
            # the megastep's reason to exist: strictly fewer blocking
            # host syncs per engagement than the plain stepping path
            plain_rt = out["breakdown"].get("host_round_trips")
            mega_rt = mega.get("host_round_trips")
            if not (
                plain_rt is not None and mega_rt is not None
                and mega_rt < plain_rt
            ):
                print(
                    f"FAIL: mega host_round_trips {mega_rt} not "
                    f"strictly below plain {plain_rt}",
                    file=sys.stderr,
                )
                sys.exit(1)
        return

    if args.tie_heavy:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        smoke = args.smoke or os.environ.get("BENCH_SMOKE") == "1"
        outs = bench_tie_heavy(
            args.reads or 8,
            args.seq_len or (600 if smoke else 10_000),
            0.02,
            iters=args.iters if args.iters != 5 else 1,
            dual_seq_len=300 if smoke else 1500,
        )
        failures = []
        for out in outs:
            out["device_platform"] = _current_platform()
            _emit(out, perfdb_kind="tie_heavy")
            if not out["parity"]:
                failures.append(f"{out['metric']}: parity lost")
            if (
                args.wall_ceiling is not None
                and out["wall_s"] > args.wall_ceiling
            ):
                failures.append(
                    f"{out['metric']}: wall {out['wall_s']}s > ceiling "
                    f"{args.wall_ceiling}s"
                )
        if failures:
            for f in failures:
                print(f"FAIL: {f}", file=sys.stderr)
            sys.exit(1)
        return

    if args.serve:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        smoke = args.smoke or os.environ.get("BENCH_SMOKE") == "1"
        out = bench_serve(
            args.serve,
            args.reads or (16 if smoke else 64),
            args.seq_len or (1000 if smoke else 2000),
            0.01,
            trace_out=args.trace_out,
            supervised=args.serve_supervised,
        )
        out["device_platform"] = _current_platform()
        _emit(out, perfdb_kind="serve")
        return

    if args.serve_mix:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        out = bench_serve_mix(args.serve_mix)
        out["device_platform"] = _current_platform()
        _emit(out, perfdb_kind="serve-mix")
        _append_mixed_w_record(out)
        return

    if args.storm:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        storm_iters = args.iters if args.iters != 5 else 2
        if args.storm_cache:
            out = bench_storm_cache(args.storm, iters=storm_iters)
            out["device_platform"] = _current_platform()
            _emit(out, perfdb_kind="storm-cache")
            if not (out["parity"] and out["exact_hits_dispatch_free"]
                    and out["hit_rate"] > 0):
                print(
                    f"FAIL: cache storm parity={out['parity']} "
                    f"dispatch_free={out['exact_hits_dispatch_free']} "
                    f"hit_rate={out['hit_rate']}",
                    file=sys.stderr,
                )
                sys.exit(1)
            return
        if args.procs:
            out = bench_storm_procs(
                args.storm,
                procs=args.procs,
                kill_worker=args.kill_worker,
                trace_out=args.trace_out,
                supervised=args.serve_supervised,
                iters=storm_iters,
            )
            out["device_platform"] = _current_platform()
            # crash drills measure degraded-mode behaviour: they land
            # as their own storm-procs-ckpt kind (migration accounting)
            # and never join the storm-procs trend baseline; fault-
            # injected (fleet-observability smoke) runs never join any
            _emit(out, perfdb_kind=None if out.get("faults") else (
                "storm-procs-ckpt" if out.get("kill_worker")
                else "storm-procs"))
            return
        out = bench_storm(
            args.storm,
            replicas=args.replicas,
            supervised=args.serve_supervised,
            iters=storm_iters,
        )
        out["device_platform"] = _current_platform()
        # fault-injected (shedding-demo) runs measure degraded-mode
        # behaviour — never let them into the rolling perf baseline
        _emit(out, perfdb_kind=None if out.get("faults") else "storm")
        return

    if args._run:
        try:
            from waffle_con_tpu.utils.cache import enable_compilation_cache

            enable_compilation_cache()
            out = bench_single(
                args.reads or 256, args.seq_len or 10_000, 0.01,
                trace=args.trace, iters=args.iters,
                trace_out=args.trace_out,
            )
            out["device_platform"] = _current_platform()
            _emit(out, perfdb_kind="north-star")
        except Exception:
            traceback.print_exc()
            sys.exit(1)
        return

    if args._gate:
        try:
            from waffle_con_tpu.utils.cache import enable_compilation_cache

            enable_compilation_cache()
            gate_start = time.perf_counter()
            checks = _parity_gate()
            print(
                json.dumps(
                    {
                        "checks": checks,
                        "wall_s": round(time.perf_counter() - gate_start, 2),
                        "platform": _current_platform(),
                    }
                )
            )
        except Exception:
            traceback.print_exc()
            sys.exit(1)
        return

    if args.grid:
        # reference criterion grid (consensus_bench.rs:9-33)
        for seq_len in (1000, 10_000):
            for num_samples in (8, 30):
                for error_rate in (0.0, 0.01, 0.02):
                    out = bench_single(
                        num_samples, seq_len, error_rate, iters=args.iters
                    )
                    out["metric"] = (
                        f"consensus_4x{seq_len}x{num_samples}_{error_rate}"
                    )
                    out["device_platform"] = _current_platform()
                    _emit(out)
        return
    if args.dual:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        out = bench_dual(
            args.reads or 64, args.seq_len or 5000, 0.01, iters=args.iters,
            trace_out=args.trace_out,
        )
        out["device_platform"] = _current_platform()
        _emit(out, perfdb_kind="dual")
        return
    if args.priority:
        from waffle_con_tpu.utils.cache import enable_compilation_cache

        enable_compilation_cache()
        out = bench_priority(
            args.reads or 32, args.seq_len or 2000, 0.01, iters=args.iters,
            trace_out=args.trace_out,
        )
        out["device_platform"] = _current_platform()
        _emit(out, perfdb_kind="priority")
        return

    _north_star_orchestrated(args)


def _current_platform() -> str:
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:  # pragma: no cover - diagnostics only
        return "unknown"


if __name__ == "__main__":
    main()
