#!/usr/bin/env python
"""Benchmarks: TPU engine vs the native C++ CPU engines (the
reference-equivalent baselines; the reference publishes no numbers —
BASELINE.md).

Default mode prints exactly ONE JSON line for the north-star config —
256 reads x 10 kb at 1% error (HiFi-like), alphabet 4, min_count =
reads/4 — with a ``breakdown`` object (device dispatch counts, run-extend
steps, band growth events, host/device wall split) and a five-scenario
parity gate (single, errored, dual split, multi split, priority chains,
per BASELINE.md).  ``vs_baseline`` > 1 is a speedup over the CPU
baseline.

Other modes (one JSON line per config):
  --grid      the reference criterion grid
              (``/root/reference/benches/consensus_bench.rs:9-33``):
              seq_len {1000, 10000} x num_samples {8, 30} x error
              {0.0, 0.01, 0.02}, alphabet 4, min_count = ns/4.
  --dual      dual-engine north-star point (two haplotypes).
  --priority  priority-chain north-star point.
  --smoke     16x1000 quick validation (also via BENCH_SMOKE=1).

``--trace DIR`` wraps the timed run in a ``jax.profiler`` trace.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _make_engine(kind, cfg, reads_or_chains):
    from waffle_con_tpu import (
        ConsensusDWFA,
        DualConsensusDWFA,
        PriorityConsensusDWFA,
    )

    engine = {
        "single": ConsensusDWFA,
        "dual": DualConsensusDWFA,
        "priority": PriorityConsensusDWFA,
    }[kind](cfg)
    for r in reads_or_chains:
        if kind == "priority":
            engine.add_sequence_chain(r)
        else:
            engine.add_sequence(r)
    return engine


def _parity_gate():
    """Five-scenario parity gate (BASELINE.md): jax-backend engines must
    reproduce the golden fixtures exactly."""
    from waffle_con_tpu import CdwfaConfigBuilder, DualConsensusDWFA
    from waffle_con_tpu.models.priority_consensus import PriorityConsensusDWFA
    from waffle_con_tpu.utils.fixtures import (
        load_dual_fixture,
        load_priority_fixture,
    )

    cfg = CdwfaConfigBuilder().wildcard(ord("*")).backend("jax").build()
    checks = {}

    def run_priority(name, include):
        chains, expected = load_priority_fixture(name, include, cfg.consensus_cost)
        engine = PriorityConsensusDWFA(cfg)
        for chain in chains:
            engine.add_sequence_chain(chain)
        got = engine.consensus()
        ok = got.sequence_indices == expected.sequence_indices and [
            [c.sequence for c in chain] for chain in got.consensuses
        ] == [[c.sequence for c in chain] for chain in expected.consensuses]
        return bool(ok)

    # single + errored + multi split + priority chains run through the
    # priority stack (as the reference's own fixture tests do)
    checks["single"] = run_priority("multi_exact_001", True)
    checks["errored"] = run_priority("multi_err_001", False)
    checks["multi_split"] = run_priority("multi_samesplit_001", True)
    checks["priority_chains"] = run_priority("priority_001", True)

    sequences, expected = load_dual_fixture("dual_001", True, cfg.consensus_cost)
    engine = DualConsensusDWFA(cfg)
    for s in sequences:
        engine.add_sequence(s)
    checks["dual_split"] = engine.consensus() == [expected]
    return checks


def bench_single(num_reads, seq_len, error_rate, parity=True, trace=None):
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.native import native_consensus
    from waffle_con_tpu.utils.example_gen import generate_test

    min_count = max(2, num_reads // 4)
    gen_start = time.perf_counter()
    truth, reads = generate_test(4, seq_len, num_reads, error_rate, seed=0)
    gen_time = time.perf_counter() - gen_start

    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder().min_count(min_count).backend(backend).build()
    )

    cpu_start = time.perf_counter()
    cpu_results = native_consensus(reads, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    # TPU engine: warm-up once (compile), then timed run
    def tpu_run():
        engine = _make_engine("single", cfg("jax"), reads)
        out = engine.consensus()
        return engine, out

    compile_start = time.perf_counter()
    engine, tpu_results = tpu_run()
    compile_time = time.perf_counter() - compile_start

    if trace:
        import jax

        jax.profiler.start_trace(trace)
    tpu_start = time.perf_counter()
    engine, tpu_results = tpu_run()
    tpu_time = time.perf_counter() - tpu_start
    if trace:
        import jax

        jax.profiler.stop_trace()

    stats = getattr(engine, "last_search_stats", {})
    counters = stats.get("scorer_counters", {})
    dispatches = sum(
        counters.get(k, 0)
        for k in (
            "push_calls", "run_calls", "stats_calls", "clone_calls",
            "activate_calls", "finalize_calls",
        )
    )
    result = {
        "metric": f"consensus_{num_reads}x{seq_len}_wall_s",
        "value": round(tpu_time, 4),
        "unit": "s",
        "vs_baseline": round(cpu_time / tpu_time, 3),
        "cpu_baseline_s": round(cpu_time, 4),
        "parity": bool(
            [(c.sequence, c.scores) for c in tpu_results] == cpu_results
        ),
        "recovered_truth": bool(
            tpu_results and tpu_results[0].sequence == truth
        ),
        "gen_s": round(gen_time, 2),
        "breakdown": {
            "warmup_incl_compile_s": round(compile_time, 2),
            "consensus_len": len(tpu_results[0].sequence) if tpu_results else 0,
            "device_dispatches": dispatches,
            "run_extend_calls": counters.get("run_calls", 0),
            "run_extend_steps": counters.get("run_steps", 0),
            "push_calls": counters.get("push_calls", 0),
            "grow_events": counters.get("grow_e_events", 0),
            "replayed_cols": counters.get("replayed_cols", 0),
            "nodes_explored": stats.get("nodes_explored", 0),
            "steps_per_s": round(
                (counters.get("run_steps", 0) + counters.get("push_calls", 0))
                / max(tpu_time, 1e-9)
            ),
        },
    }
    if parity:
        gate = _parity_gate()
        result["parity_gate"] = gate
        result["parity"] = bool(result["parity"] and all(gate.values()))
    return result


def bench_dual(num_reads, seq_len, error_rate):
    """Dual north-star: two haplotypes differing by 3 SNPs, half the reads
    each; CPU baseline is the complete C++ dual engine."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.native import native_dual_consensus
    from waffle_con_tpu.utils.example_gen import generate_test
    import numpy as np

    rng = np.random.default_rng(1)
    truth, reads1 = generate_test(4, seq_len, num_reads // 2, error_rate, seed=1)
    h2 = bytearray(truth)
    for pos in rng.choice(seq_len, size=3, replace=False):
        h2[pos] = (h2[pos] + 1 + rng.integers(3)) % 4
    h2 = bytes(h2)
    from waffle_con_tpu.utils.example_gen import corrupt

    reads2 = [
        corrupt(h2, error_rate, np.random.default_rng(100 + i))
        for i in range(num_reads // 2)
    ]
    reads = list(reads1) + reads2

    min_count = max(2, num_reads // 4)
    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder().min_count(min_count).backend(backend).build()
    )

    cpu_start = time.perf_counter()
    cpu_results = native_dual_consensus(reads, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    def tpu_run():
        return _make_engine("dual", cfg("jax"), reads).consensus()

    tpu_results = tpu_run()
    tpu_start = time.perf_counter()
    tpu_results = tpu_run()
    tpu_time = time.perf_counter() - tpu_start

    return {
        "metric": f"dual_{num_reads}x{seq_len}_wall_s",
        "value": round(tpu_time, 4),
        "unit": "s",
        "vs_baseline": round(cpu_time / tpu_time, 3),
        "cpu_baseline_s": round(cpu_time, 4),
        "parity": bool(tpu_results == cpu_results),
        "is_dual": bool(tpu_results and tpu_results[0].is_dual()),
    }


def bench_priority(num_reads, seq_len, error_rate):
    """Priority north-star: 2-level chains splitting into two groups."""
    from waffle_con_tpu import CdwfaConfigBuilder
    from waffle_con_tpu.native import native_priority_consensus
    from waffle_con_tpu.utils.example_gen import generate_test, corrupt
    import numpy as np

    truth, level0 = generate_test(4, seq_len // 2, num_reads, error_rate, seed=3)
    t1a, _ = generate_test(4, seq_len, 1, 0.0, seed=4)
    t1b = bytearray(t1a)
    t1b[seq_len // 3] = (t1b[seq_len // 3] + 1) % 4
    t1b[2 * seq_len // 3] = (t1b[2 * seq_len // 3] + 2) % 4
    t1b = bytes(t1b)
    chains = []
    for i in range(num_reads):
        level1_truth = t1a if i < num_reads // 2 else t1b
        lvl1 = corrupt(level1_truth, error_rate, np.random.default_rng(200 + i))
        chains.append([level0[i], lvl1])

    min_count = max(2, num_reads // 4)
    cfg = lambda backend: (  # noqa: E731
        CdwfaConfigBuilder().min_count(min_count).backend(backend).build()
    )

    cpu_start = time.perf_counter()
    cpu_result = native_priority_consensus(chains, config=cfg("native"))
    cpu_time = time.perf_counter() - cpu_start

    def tpu_run():
        return _make_engine("priority", cfg("jax"), chains).consensus()

    tpu_result = tpu_run()
    tpu_start = time.perf_counter()
    tpu_result = tpu_run()
    tpu_time = time.perf_counter() - tpu_start

    return {
        "metric": f"priority_{num_reads}x{seq_len}_wall_s",
        "value": round(tpu_time, 4),
        "unit": "s",
        "vs_baseline": round(cpu_time / tpu_time, 3),
        "cpu_baseline_s": round(cpu_time, 4),
        "parity": bool(tpu_result == cpu_result),
        "groups": len(tpu_result.consensuses),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--grid", action="store_true")
    parser.add_argument("--dual", action="store_true")
    parser.add_argument("--priority", action="store_true")
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument("--trace", default=None)
    args = parser.parse_args()

    if args.grid:
        # reference criterion grid (consensus_bench.rs:9-33)
        for seq_len in (1000, 10_000):
            for num_samples in (8, 30):
                for error_rate in (0.0, 0.01, 0.02):
                    out = bench_single(
                        num_samples, seq_len, error_rate, parity=False
                    )
                    out["metric"] = (
                        f"consensus_4x{seq_len}x{num_samples}_{error_rate}"
                    )
                    print(json.dumps(out))
        return
    if args.dual:
        print(json.dumps(bench_dual(64, 5000, 0.01)))
        return
    if args.priority:
        print(json.dumps(bench_priority(32, 2000, 0.01)))
        return

    smoke = args.smoke or os.environ.get("BENCH_SMOKE") == "1"
    num_reads = 16 if smoke else 256
    seq_len = 1000 if smoke else 10_000
    print(
        json.dumps(
            bench_single(num_reads, seq_len, 0.01, trace=args.trace)
        )
    )


if __name__ == "__main__":
    main()
